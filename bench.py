"""Benchmark entry (driver-run on real TPU hardware).

Measures BASELINE.md configs on a single chip:
 - configs[0]: ResNet-50 training throughput, CIFAR-10-shaped data
   (batch 256, 3x32x32), images/sec  -> the headline "value".
 - configs[3]-class: GPT-345M causal-LM training, seq 1024, bf16 AMP,
   tokens/sec/chip + MFU — the transformer fast path the framework is for.
 - BERT-base finetune step, ring attention at S=8192, and the packed
   ragged-varlen flash kernel vs its padded equivalent.

Each train step (forward + backward + optimizer update) is ONE jitted XLA
program with bf16 AMP. MFU comes from XLA's own cost analysis vs the chip's
public bf16 peak (plus the analytic 6N model MFU for GPT, since XLA cannot
see Pallas FLOPs).

Architecture (BENCH r01/r02/r04 post-mortems — three rounds of rc=1):
the PARENT PROCESS NEVER INITIALIZES JAX. Every device-touching leg runs
in its own subprocess with a hard watchdog timeout, so a hanging tunnel
(observed: ``jax.local_devices()`` blocking >6 min) costs one leg, not
the run. The merged JSON line is re-printed after EVERY leg — if the
driver kills the run mid-leg, the last stdout line still carries every
number measured so far. A canary failure downgrades to a reduced leg
list rather than skipping TPU entirely. rc=0 iff at least one
throughput number was measured.

Prints its json line (last line = most complete):
{"metric", "value", "unit", "vs_baseline", ...}.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback
from paddle_tpu.device import enable_overlap_flags as _enable_overlap_flags
from paddle_tpu.distributed._jax_compat import shard_map as _shard_map, use_mesh as _use_mesh

# latency-hiding-scheduler / async-collective flags must precede backend
# init; idempotent + env-gated, no-op off TPU (device/xla_flags.py)
_enable_overlap_flags()

SMOKE = bool(os.environ.get("BENCH_SMOKE"))  # tiny-shape CI structure check
RESNET_BATCH = 8 if SMOKE else 256
GPT_SEQ = 64 if SMOKE else 1024
BERT_SEQ = 128
WARMUP = 1 if SMOKE else 5
ITERS = 2 if SMOKE else 15       # steps per timed block
BLOCKS = 1 if SMOKE else 3       # timed blocks -> min/median/max spread

_HERE = os.path.dirname(os.path.abspath(__file__))
_GPT_CACHE = os.path.join(_HERE, ".bench_gpt_best.json")

# Wall-clock budget for the whole script. The driver's patience is finite
# (r04 died with nothing); finish inside it and print what we have.
BUDGET_SEC = float(os.environ.get("BENCH_BUDGET_SEC",
                                  "900" if SMOKE else "2700"))

# Per-leg watchdog timeouts (seconds). GPT-345M compile alone is
# ~75-100 s over the tunnel; timing adds ~3 blocks * 15 steps * ~0.3 s.
_T = (lambda full, smoke: smoke if SMOKE else full)
LEG_TIMEOUT = {
    "canary": _T(300, 120), "canary_retry": _T(420, 120),
    "resnet": _T(600, 300), "gpt": _T(900, 300), "bert": _T(600, 300),
    "ring": _T(600, 300), "packed": _T(600, 300), "kernels": _T(600, 300),
}

# Driver-captured r03 numbers (BENCH_r03.json, 2026-07-30) — the
# reproducible baseline this build is measured against. vs_baseline is
# measured/THIS, so >1.0 means faster than the last driver capture.
_DRIVER_BASELINE = {
    "resnet50_img_per_sec": 152580.22,
    "gpt345m_tokens_per_sec": 17176.5,
    "bert_base_seq_per_sec": 809.1,
}

# bf16 peak FLOP/s per chip: the ONE shared table lives in
# observability.trace (PEAK_FLOPS) so bench records and the
# pt_mfu_analytic gauge can never disagree about a chip's peak
from paddle_tpu.observability.trace import peak_flops as _peak_flops  # noqa: E402


def _error_tail(tb: str) -> str:
    """Last *informative* line of a traceback: jax/XLA errors often end
    with decorative ===/--- rules (the BENCH_r03 gpt error recorded just
    '==========' before this existed)."""
    lines = [ln.strip() for ln in tb.strip().splitlines()]
    for ln in reversed(lines):
        if ln and any(c.isalnum() for c in ln):
            return ln[:400]
    return (lines[-1] if lines else "")[:400]


def _is_oom_str(s: str) -> bool:
    return any(t in s for t in (
        "RESOURCE_EXHAUSTED", "Resource exhausted", "out of memory",
        "Out of memory", "OOM", "Allocation failure",
        "exceeds the memory capacity", "exceeds available memory"))


def _honor_cpu_override():
    """The environment's sitecustomize force-registers the TPU-tunnel
    backend via jax.config (overriding the JAX_PLATFORMS env var); when
    the caller explicitly asked for cpu, re-assert it before any backend
    initializes."""
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass


def _flops_per_step(compiled):
    """Model FLOPs per step from XLA's own cost analysis (None if n/a)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0)) or None
    except Exception:
        return None


def _memory_report(compiled):
    """Per-step HBM footprint from XLA's memory analysis (the L1
    peak-memory reporting: arguments = resident state, temp = activation
    working set)."""
    try:
        ma = compiled.memory_analysis()
        return {
            "argument_bytes": int(getattr(ma, "argument_size_in_bytes", 0)),
            "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
            "output_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
        }
    except Exception:
        return None


def _feed_tracer(program, flops, step_seconds):
    """Feed the step tracer the leg's measured program cost so the
    record's ``trace`` block (and pt_mfu_analytic) agrees with the
    leg's own MFU arithmetic."""
    from paddle_tpu.observability.trace import get_tracer
    tr = get_tracer()
    if not tr.enabled:
        return
    if flops:
        tr.record_program_flops(program, flops)
    if step_seconds:
        tr.on_step(step_seconds)


def _device_kind():
    import jax
    return jax.local_devices()[0].device_kind


def _fetch_scalar(out):
    """HOST READBACK of the step's loss — the only trustworthy fence.
    On the remote-tunnel backend ``block_until_ready`` can return without
    waiting and identical repeated executions can be served from a
    cache; threading state forward + pulling a scalar defeats both
    (measured r04: a broken fence reported 5.76ms for a 17-TFLOP step)."""
    import numpy as np
    return float(np.asarray(out[0]))


_FENCE_STATE = {}


def _fence_cost():
    """Round-trip latency of one scalar readback, measured on a FRESH
    tiny computation each call (re-fetching an already-fetched jax.Array
    returns its cached host value in microseconds, and repeating an
    identical execution can be served from the tunnel's cache — both
    would fake a near-zero fence)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    if "fn" not in _FENCE_STATE:
        _FENCE_STATE["fn"] = jax.jit(lambda s: s * 1.000001 + 1e-9)
        _FENCE_STATE["x"] = jnp.float32(1.234)
        _FENCE_STATE["x"] = _FENCE_STATE["fn"](_FENCE_STATE["x"])
        float(np.asarray(_FENCE_STATE["x"]))  # compile + warm
    costs = []
    for _ in range(2):
        t0 = time.perf_counter()
        _FENCE_STATE["x"] = _FENCE_STATE["fn"](_FENCE_STATE["x"])
        float(np.asarray(_FENCE_STATE["x"]))
        costs.append(time.perf_counter() - t0)
    return min(costs)


def _time_compiled(compiled, args, n_state):
    """Warmup + BLOCKS timed blocks of ITERS steps, each fenced by a
    loss readback whose latency is measured and subtracted. The step's
    first n_state outputs feed back as its first n_state inputs (fresh
    buffers every call). Returns (per_step_seconds_list, final_out)."""
    state = list(args[:n_state])
    rest = list(args[n_state:])
    out = None
    for _ in range(WARMUP):
        out = compiled(*state, *rest)
        state = list(out[1:1 + n_state])
    _fetch_scalar(out)
    times = []
    for _ in range(BLOCKS):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            out = compiled(*state, *rest)
            state = list(out[1:1 + n_state])
        _fetch_scalar(out)
        dt = time.perf_counter() - t0
        fence = _fence_cost()
        times.append(max(dt - fence, 1e-9) / ITERS)
    from paddle_tpu.observability import get_telemetry
    tel = get_telemetry()
    for t in times:  # block-averaged step times -> step histogram/p50/p95
        tel.observe_step(t, mode="bench")
    return times, out


def _spread_ms(times):
    s = sorted(t * 1000 for t in times)
    return {"min": round(s[0], 2), "median": round(s[len(s) // 2], 2),
            "max": round(s[-1], 2)}


def _cluster_snapshot():
    """Aggregated cluster view for the record: skew, per-rank step
    p50/p95, total recompiles — from a running aggregator when
    PT_AGGREGATOR_URL is set, else a single-rank local summary.  Must
    never sink a bench run: failures come back as {"error": ...}."""
    try:
        from paddle_tpu.observability import cluster_snapshot
        return cluster_snapshot(
            url=os.environ.get("PT_AGGREGATOR_URL") or None)
    except Exception as e:  # snapshot is best-effort by contract
        return {"error": str(e)[:200]}


# ---------------------------------------------------------------------------
# Legs (each runs inside its own subprocess; writes into `result`)
# ---------------------------------------------------------------------------

def leg_canary(result):
    """Tiny matmul on the device: proves the tunnel is alive and records
    the device kind. Must be cheap — it is the gatekeeper the heavy legs
    consult, not a benchmark."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    result["device_kind"] = _device_kind()
    x = jnp.ones((256, 256), jnp.bfloat16)
    y = jax.jit(lambda a: a @ a)(x)
    assert float(np.asarray(y[0, 0])) == 256.0
    result["canary_ok"] = True


def bench_resnet(result):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.jit.api import functional_call
    from paddle_tpu.tensor import Tensor

    result["device_kind"] = _device_kind()
    pt.seed(0)
    net = pt.vision.models.resnet50(num_classes=10)
    pt.amp.decorate(net, level="O2", dtype="bfloat16")
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=net.parameters(),
                                multi_precision=True)
    params = {k: p._data for k, p in net.named_parameters()}
    buffers = {k: b._data for k, b in net.named_buffers()}
    opt_state = opt.init_state_tree(params)
    fwd = getattr(net, "_orig_forward", net.forward)

    def train_step(params, buffers, opt_state, x, y):
        def loss_of(p):
            out, new_buffers = functional_call(
                net, p, buffers, (Tensor(x),), training=True, forward_fn=fwd)
            logits = out._data.astype(jnp.float32)
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
            return loss, new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_tree(params, grads,
                                                       opt_state)
        return loss, new_params, new_buffers, new_opt

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(RESNET_BATCH, 3, 32, 32)
                    .astype(np.float32)).astype(jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 10, RESNET_BATCH).astype(np.int32))

    t0 = time.perf_counter()
    compiled = step.lower(params, buffers, opt_state, x, y).compile()
    result["resnet50_compile_sec"] = round(time.perf_counter() - t0, 2)
    flops = _flops_per_step(compiled)
    result["resnet50_flops_per_step"] = flops
    result["resnet50_memory"] = _memory_report(compiled)

    times, _ = _time_compiled(compiled, (params, buffers, opt_state, x, y),
                              3)
    result["resnet50_step_ms"] = _spread_ms(times)
    step = sorted(times)[len(times) // 2]
    ips = RESNET_BATCH / step
    result["value"] = round(ips, 2)
    result["vs_baseline"] = round(
        ips / _DRIVER_BASELINE["resnet50_img_per_sec"], 3)
    peak = _peak_flops(result.get("device_kind"))
    if flops and peak:
        result["mfu"] = round(flops / step / peak, 4)
    _feed_tracer("resnet50_step", flops, step)
    return ips


def bench_gpt(result, batch, recompute=True):
    """GPT-345M-class train step (bf16, seq 1024) — tokens/sec/chip + MFU."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.jit.api import functional_call
    from paddle_tpu.tensor import Tensor
    from paddle_tpu.incubate.models import (GPTForCausalLM,
                                            GPTPretrainingCriterion,
                                            gpt_345m)

    result["device_kind"] = _device_kind()
    pt.seed(0)
    if SMOKE:
        from paddle_tpu.incubate.models import gpt_tiny
        cfg = gpt_tiny(tensor_parallel=False, use_recompute=recompute)
    else:
        cfg = gpt_345m(tensor_parallel=False, use_recompute=recompute,
                       max_position_embeddings=GPT_SEQ)
    result["gpt345m_recompute"] = recompute
    model = GPTForCausalLM(cfg)
    pt.amp.decorate(model, level="O2", dtype="bfloat16")
    crit = GPTPretrainingCriterion()
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True)
    params = {k: p._data for k, p in model.named_parameters()}
    buffers = {k: b._data for k, b in model.named_buffers()}
    opt_state = opt.init_state_tree(params)
    fwd = getattr(model, "_orig_forward", model.forward)
    n_params = sum(int(np.prod(p.shape)) for p in params.values())
    result["gpt345m_n_params"] = n_params

    # the graph-level fusion pass wraps the LOSS function (not the whole
    # step): grad-side consumption of forward intermediates would break
    # cluster closure on the whole-step jaxpr, while wrapping loss_of
    # lets the fused kernels' custom VJPs own the backward
    from paddle_tpu.ops import fusion_pass as _fusion
    _fusion.reset_stats()

    def train_step(params, buffers, opt_state, ids, labels):
        def loss_of(p):
            out, new_buffers = functional_call(
                model, p, buffers, (Tensor(ids),), training=True,
                forward_fn=fwd)
            loss = crit(out, Tensor(labels))
            return loss._data.astype(jnp.float32), new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            _fusion.wrap(loss_of), has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_tree(params, grads,
                                                       opt_state)
        return loss, new_params, new_buffers, new_opt

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, GPT_SEQ))
                      .astype(np.int32))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, GPT_SEQ))
                         .astype(np.int32))

    t0 = time.perf_counter()
    traced = step.trace(params, buffers, opt_state, ids, labels)
    compiled = traced.lower().compile()
    result["gpt345m_compile_sec"] = round(time.perf_counter() - t0, 2)
    # fusion block: which patterns got rewritten at trace time, and which
    # fell back to the XLA mirror (tpu_unreachable on the CPU fast-fail
    # path, canary_failed when Mosaic rejects a kernel)
    result["fusion"] = _fusion.summary()
    # graph audit: the AOT trace above already holds the step jaxpr, so
    # the auditor costs zero extra traces here (compile-time only)
    from paddle_tpu.tools.audit import runtime as _audit
    if _audit.audit_enabled():
        from paddle_tpu.tools.audit.core import AuditProgram
        n_donated = len(jax.tree_util.tree_leaves(
            (params, buffers, opt_state)))
        _audit.audit_program(AuditProgram(
            name="bench_gpt_step", jaxpr=traced.jaxpr, kind="capture",
            donated=range(n_donated),
            fusion_expected=_fusion.fusion_enabled(),
            fusion_rewrites=result["fusion"].get("rewrites")))
    flops = _flops_per_step(compiled)
    result["gpt345m_flops_per_step"] = flops
    result["gpt345m_memory"] = _memory_report(compiled)

    times, _ = _time_compiled(compiled,
                              (params, buffers, opt_state, ids, labels), 3)
    result["gpt345m_step_ms"] = _spread_ms(times)
    step = sorted(times)[len(times) // 2]
    tps = batch * GPT_SEQ / step
    result["gpt345m_tokens_per_sec"] = round(tps, 1)
    result["gpt345m_vs_baseline"] = round(
        tps / _DRIVER_BASELINE["gpt345m_tokens_per_sec"], 3)
    result["gpt345m_batch"] = batch
    result["gpt345m_seq"] = GPT_SEQ
    peak = _peak_flops(result.get("device_kind"))
    if flops and peak:
        # hardware utilization per XLA's cost analysis. Caveat: custom
        # Pallas kernels (flash attention) report no flops to XLA, so
        # this undercounts when the flash path is active.
        result["gpt345m_mfu"] = round(flops / step / peak, 4)
    if peak:
        # standard analytic MFU: 6N per token fwd+bwd + causal attention
        # 6*L*S*H (recomputed FLOPs deliberately NOT counted — the
        # convention used by the public scaling literature)
        per_token = (6 * n_params
                     + 6 * cfg.num_layers * GPT_SEQ * cfg.hidden_size)
        result["gpt345m_mfu_model"] = round(tps * per_token / peak, 4)
    _feed_tracer("gpt345m_step", flops, step)
    return tps


def bench_bert(result, batch):
    """BERT-base SST-2-style finetune step (config[1]): seq/sec via the
    compiled (to_static-equivalent) path, bf16 AMP."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.jit.api import functional_call
    from paddle_tpu.tensor import Tensor
    from paddle_tpu.incubate.models import (BertForSequenceClassification,
                                            bert_base, bert_tiny)

    result["device_kind"] = _device_kind()
    pt.seed(0)
    cfg = bert_tiny() if SMOKE else bert_base()
    model = BertForSequenceClassification(cfg, num_classes=2)
    pt.amp.decorate(model, level="O2", dtype="bfloat16")
    opt = pt.optimizer.AdamW(learning_rate=2e-5,
                             parameters=model.parameters(),
                             multi_precision=True)
    params = {k: p._data for k, p in model.named_parameters()}
    buffers = {k: b._data for k, b in model.named_buffers()}
    opt_state = opt.init_state_tree(params)
    fwd = getattr(model, "_orig_forward", model.forward)
    seq = 32 if SMOKE else BERT_SEQ

    def train_step(params, buffers, opt_state, ids, y):
        def loss_of(p):
            out, new_buffers = functional_call(
                model, p, buffers, (Tensor(ids),), training=True,
                forward_fn=fwd)
            logits = out._data.astype(jnp.float32)
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
            return loss, new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_tree(params, grads,
                                                       opt_state)
        return loss, new_params, new_buffers, new_opt

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq))
                      .astype(np.int32))
    y = jnp.asarray(rng.randint(0, 2, batch).astype(np.int32))

    t0 = time.perf_counter()
    compiled = step.lower(params, buffers, opt_state, ids, y).compile()
    result["bert_base_compile_sec"] = round(time.perf_counter() - t0, 2)
    flops = _flops_per_step(compiled)
    result["bert_base_flops_per_step"] = flops
    result["bert_base_memory"] = _memory_report(compiled)

    times, _ = _time_compiled(compiled, (params, buffers, opt_state, ids, y),
                              3)
    result["bert_base_step_ms"] = _spread_ms(times)
    step = sorted(times)[len(times) // 2]
    sps = batch / step
    result["bert_base_seq_per_sec"] = round(sps, 1)
    result["bert_base_vs_baseline"] = round(
        sps / _DRIVER_BASELINE["bert_base_seq_per_sec"], 3)
    result["bert_base_batch"] = batch
    result["bert_base_seq_len"] = seq
    peak = _peak_flops(result.get("device_kind"))
    if flops and peak:
        result["bert_base_mfu"] = round(flops / step / peak, 4)
    _feed_tracer("bert_base_step", flops, step)
    return sps


def bench_ring(result):
    """Ring-attention leg: the Pallas flash kernel driven through the
    shard_map ring schedule on the real chip (1-device mesh still
    exercises the kernel lowering + collective plumbing), S=8192 —
    the long-context path BENCH r03 never touched.

    Also records the compiled program's temp bytes: ring attention's
    working set must stay O(S_local * block) — far below the O(S^2)
    logits buffer a dense attention would need at this length."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.distributed.auto_parallel.spec_layout import \
        default_layout
    from paddle_tpu.distributed.fleet.meta_parallel.sequence_parallel \
        import ring_attention

    result["device_kind"] = _device_kind()
    B, H, S, D = 1, 16, 512 if SMOKE else 8192, 64
    mesh = Mesh(np.array(jax.devices()[:1]), ("sep",))
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)).astype(
        jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)).astype(
        jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)).astype(
        jnp.bfloat16)

    def fwd_bwd(q, k, v):
        def loss(q):
            ring_spec = default_layout().seq_heads(ndim=4, seq_dim=2)
            out = _shard_map(
                lambda a, b, c: ring_attention(a, b, c, causal=True),
                mesh=mesh, in_specs=(ring_spec,) * 3,
                out_specs=ring_spec)(q, k, v)
            return jnp.sum(out.astype(jnp.float32)), out
        (s, out), dq = jax.value_and_grad(loss, has_aux=True)(q)
        return s, dq

    step = jax.jit(fwd_bwd)
    t0 = time.perf_counter()
    compiled = step.lower(q, k, v).compile()
    result["ring_attn_compile_sec"] = round(time.perf_counter() - t0, 2)
    result["ring_attn_memory"] = _memory_report(compiled)

    def run(qq):
        s, dq = compiled(qq, k, v)
        return s, (dq.astype(jnp.float32) * 1e-3).astype(qq.dtype)

    s, qq = run(q)
    float(np.asarray(s))
    iters = 2 if SMOKE else 8
    t0 = time.perf_counter()
    for _ in range(iters):
        s, qq = run(qq)
    float(np.asarray(s))
    dt = time.perf_counter() - t0
    fence = _fence_cost()
    ms = max(dt - fence, 1e-9) / iters * 1000
    result["ring_attn_fwdbwd_ms"] = round(ms, 2)
    result["ring_attn_seq"] = S
    # sanity: the temp working set must be far below the O(S^2) dense
    # logits buffer (B*H*S*S bf16)
    mem = result.get("ring_attn_memory") or {}
    dense_logits_bytes = 2 * B * H * S * S
    result["ring_attn_temp_vs_dense_logits"] = round(
        mem.get("temp_bytes", 0) / dense_logits_bytes, 4) \
        if mem.get("temp_bytes") else None
    return ms


def bench_packed(result):
    """Packed ragged-varlen flash attention on the real chip — the r04
    kernel that until now only ever ran in interpret mode.

    Mixed lengths 64..1024 (sum 3392 vs 8*1024=8192 padded tokens;
    sum len^2 is 3.6x below B*max^2), fwd+bwd through all three packed
    kernels (fwd/dq/dkv), vs the SAME data through the padded batched
    flash kernel. Valid rows of both paths must agree (parity recorded),
    and packed should win by skipping off-band tiles."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_ops import mha, mha_packed

    result["device_kind"] = _device_kind()
    H, D = 16, 64
    lens = [16, 32, 48, 24] if SMOKE else [64, 128, 896, 256, 1024, 192,
                                           512, 320]
    B, mx = len(lens), max(lens)
    total = sum(lens)
    cu = jnp.asarray(np.concatenate([[0], np.cumsum(lens)]).astype(np.int32))
    rng = np.random.RandomState(0)
    qp = jnp.asarray(rng.randn(total, H, D).astype(np.float32)).astype(
        jnp.bfloat16)
    kp = jnp.asarray(rng.randn(total, H, D).astype(np.float32)).astype(
        jnp.bfloat16)
    vp = jnp.asarray(rng.randn(total, H, D).astype(np.float32)).astype(
        jnp.bfloat16)
    # the same tokens scattered to a padded (B, H, mx, D) batch (mha's
    # layout); advanced indexing at axes 0/2 broadcasts (total, H, D)
    rows = np.concatenate([np.full(L, i) for i, L in enumerate(lens)])
    cols = np.concatenate([np.arange(L) for L in lens])

    def pad_batch(x):
        buf = jnp.zeros((B, H, mx, D), x.dtype)
        return buf.at[rows, :, cols].set(x)

    qb, kb, vb = pad_batch(qp), pad_batch(kp), pad_batch(vp)

    interp = None if SMOKE else False  # SMOKE runs on CPU via interpret

    def packed_fb(q):
        def loss(q):
            out = mha_packed(q, kp, vp, cu, cu, causal=True,
                             interpret=interp)
            return jnp.sum(out.astype(jnp.float32)), out
        (s, out), dq = jax.value_and_grad(loss, has_aux=True)(q)
        return s, out, dq

    def padded_fb(q):
        def loss(q):
            out = mha(q, kb, vb, causal=True, interpret=interp)
            return jnp.sum(out.astype(jnp.float32)), out
        (s, out), dq = jax.value_and_grad(loss, has_aux=True)(q)
        return s, out, dq

    cpk = jax.jit(packed_fb).lower(qp).compile()
    cpd = jax.jit(padded_fb).lower(qb).compile()
    result["packed_varlen_memory"] = _memory_report(cpk)

    # parity on valid rows (fwd outputs; bf16 tolerance)
    _, op, _ = cpk(qp)
    _, ob, _ = cpd(qb)
    err = float(jnp.max(jnp.abs(
        op.astype(jnp.float32) - ob[rows, :, cols].astype(jnp.float32))))
    result["packed_varlen_parity_err"] = round(err, 4)

    def timed(compiled, q0):
        s, _, dq = compiled(q0)
        float(np.asarray(s))
        qq, iters = q0, 2 if SMOKE else 10
        t0 = time.perf_counter()
        for _ in range(iters):
            s, _, dq = compiled(qq)
            qq = (qq.astype(jnp.float32)
                  + dq.astype(jnp.float32) * 1e-3).astype(qq.dtype)
        float(np.asarray(s))
        dt = time.perf_counter() - t0
        return max(dt - _fence_cost(), 1e-9) / iters * 1000

    ms_packed = timed(cpk, qp)
    ms_padded = timed(cpd, qb)
    result["packed_varlen_fwdbwd_ms"] = round(ms_packed, 2)
    result["padded_equiv_fwdbwd_ms"] = round(ms_padded, 2)
    result["packed_varlen_speedup"] = round(ms_padded / ms_packed, 2)
    result["packed_varlen_tokens_per_sec"] = round(
        total / (ms_packed / 1000), 1)
    result["packed_varlen_lens"] = lens
    return ms_packed


def bench_kernels(result):
    """Fusion-cluster microbench: each fused Pallas kernel vs the XLA
    lowering of its pure-jnp reference, fwd+bwd, at the bench models'
    shapes (GPT-345M hidden/vocab, BERT hidden, ResNet50 head). The
    autotuner searches launch configs first — winning config, search
    seconds, and timed/pruned counts ride on the record's ``autotune``
    block — then the timed runs consume the cached winners exactly like
    a real train step would."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import autotune as at
    from paddle_tpu.ops import fused_kernels as fk
    from paddle_tpu.ops.pallas_ops import mha, mha_reference, tune_mha

    result["device_kind"] = _device_kind()
    interp = None if SMOKE else False  # SMOKE runs on CPU via interpret
    iters = 2 if SMOKE else 20
    rng = np.random.RandomState(0)
    kernels: dict = {}

    def fwdbwd_ms(fn, *args):
        f = jax.jit(jax.grad(
            lambda *a: jnp.sum(fn(*a).astype(jnp.float32))))
        jax.block_until_ready(f(*args))
        t0 = time.perf_counter()
        for _ in range(iters):
            g = f(*args)
        jax.block_until_ready(g)
        return (time.perf_counter() - t0) / iters * 1000

    def record(name, pallas_ms, xla_ms):
        kernels[name] = {"pallas_ms": round(pallas_ms, 3),
                         "xla_ms": round(xla_ms, 3),
                         "speedup": round(xla_ms / max(pallas_ms, 1e-9), 2)}

    # -- fused layernorm: GPT-345M and BERT token×hidden shapes --------
    ln_shapes = [("gpt345m", 8 * GPT_SEQ, 1024), ("bert", 32 * BERT_SEQ,
                                                  768)]
    for tag, rows, d in ln_shapes:
        if SMOKE:
            rows, d = min(rows, 512), min(d, 256)
        x = jnp.asarray(rng.randn(rows, d).astype(np.float32)).astype(
            jnp.bfloat16)
        w = jnp.ones((d,), jnp.bfloat16)
        b = jnp.zeros((d,), jnp.bfloat16)
        fk.tune_layer_norm(x, w, b, interpret=interp)
        record(f"fused_layer_norm_{tag}",
               fwdbwd_ms(lambda a: fk.fused_layer_norm(
                   a, w, b, interpret=interp), x),
               fwdbwd_ms(lambda a: fk.layer_norm_reference(a, w, b), x))

    # -- fused-block rows: residual+LN (the fusion pass's residual_ln
    # cluster — in-kernel add before the stats) at the same shapes ------
    for tag, rows, d in ln_shapes:
        if SMOKE:
            rows, d = min(rows, 512), min(d, 256)
        x = jnp.asarray(rng.randn(rows, d).astype(np.float32)).astype(
            jnp.bfloat16)
        r = jnp.asarray(rng.randn(rows, d).astype(np.float32)).astype(
            jnp.bfloat16)
        w = jnp.ones((d,), jnp.bfloat16)
        b = jnp.zeros((d,), jnp.bfloat16)
        record(f"residual_ln_{tag}",
               fwdbwd_ms(lambda a, rr: fk.fused_layer_norm(
                   a, w, b, residual=rr, interpret=interp), x, r),
               fwdbwd_ms(lambda a, rr: fk.layer_norm_reference(
                   a, w, b, residual=rr), x, r))

    # -- fused softmax-xent: GPT vocab, BERT vocab, ResNet50 head ------
    xe_shapes = [("gpt345m", 1024, 50304), ("bert", 1024, 30592),
                 ("resnet50_head", 256, 1000)]
    for tag, rows, V in xe_shapes:
        if SMOKE:
            rows, V = min(rows, 64), min(V, 512)
        logits = jnp.asarray(
            rng.randn(rows, V).astype(np.float32)).astype(jnp.bfloat16)
        lab = jnp.asarray(rng.randint(0, V, rows).astype(np.int32))
        fk.tune_softmax_xent(logits, lab, interpret=interp)
        record(f"fused_softmax_xent_{tag}",
               fwdbwd_ms(lambda a: fk.fused_softmax_xent(
                   a, lab, interpret=interp), logits),
               fwdbwd_ms(lambda a: fk.softmax_xent_reference(a, lab),
                         logits))

    # -- flash attention at the GPT-345M attention shape ---------------
    S = GPT_SEQ
    q, k, v = (jnp.asarray(rng.randn(1, 16, S, 64).astype(
        np.float32)).astype(jnp.bfloat16) for _ in range(3))
    tune_mha(q, k, v, causal=True, interpret=interp)
    record("flash_mha_gpt345m",
           fwdbwd_ms(lambda a: mha(a, k, v, causal=True,
                                   interpret=interp), q),
           fwdbwd_ms(lambda a: mha_reference(a, k, v, causal=True), q))

    # -- attention-block cluster (qk+scale+softmax+pv, the fusion
    # pass's attention_block rewrite target) at GPT and BERT shapes ----
    attn_shapes = [("gpt345m", 16, GPT_SEQ, True),
                   ("bert", 12, BERT_SEQ, False)]
    for tag, heads, seq, causal in attn_shapes:
        if SMOKE:
            heads, seq = min(heads, 4), min(seq, 64)
        q2, k2, v2 = (jnp.asarray(rng.randn(1, heads, seq, 64).astype(
            np.float32)).astype(jnp.bfloat16) for _ in range(3))
        tune_mha(q2, k2, v2, causal=causal, interpret=interp)
        record(f"attention_block_{tag}",
               fwdbwd_ms(lambda a: fk.fused_attention_block(
                   a, k2, v2, causal=causal, interpret=interp), q2),
               fwdbwd_ms(lambda a: fk.attention_block_reference(
                   a, k2, v2, causal=causal), q2))

    result["kernels"] = kernels
    result["autotune"] = at.summary()
    return kernels


# ---------------------------------------------------------------------------
# Leg subprocess plumbing
# ---------------------------------------------------------------------------

def _leg_main(name, batch, recompute):
    """Child entry: run one leg, print one JSON line, exit 0 always
    (errors travel in the JSON)."""
    _honor_cpu_override()
    from paddle_tpu.observability import get_telemetry
    from paddle_tpu.observability.trace import get_tracer
    from paddle_tpu.observability.goodput import get_goodput
    from paddle_tpu.observability.numerics import get_monitor
    from paddle_tpu.observability.sdc import get_monitor as sdc_monitor
    from paddle_tpu.observability.memory import get_memory_monitor
    from paddle_tpu.tools.audit import runtime as audit_rt
    tel = get_telemetry().enable()  # metrics + compile watch, no sink/server
    tr = get_tracer().enable()      # span sink + analytic-MFU accounting
    gp = get_goodput().enable()     # wall-clock decomposition over spans
    mm = get_memory_monitor().enable()  # footprints + watermarks + OOM
    audit_rt.enable()               # graph audit at capture/serve compiles
    fields: dict = {}
    rec = {"ok": True, "fields": fields}
    try:
        if name == "canary":
            leg_canary(fields)
        elif name == "resnet":
            bench_resnet(fields)
        elif name == "gpt":
            bench_gpt(fields, batch, recompute=recompute)
        elif name == "bert":
            bench_bert(fields, batch)
        elif name == "ring":
            bench_ring(fields)
        elif name == "packed":
            bench_packed(fields)
        elif name == "kernels":
            bench_kernels(fields)
        else:
            raise ValueError(f"unknown leg {name}")
    except Exception:
        tb = traceback.format_exc(limit=20)
        rec["ok"] = False
        rec["error"] = _error_tail(tb)
        rec["oom"] = _is_oom_str(tb)
    # health snapshot rides along even when the leg died: compile count,
    # step p50/p95, peak device memory at the moment of failure
    fields[f"telemetry_{name}"] = tel.snapshot()
    fields[f"trace_{name}"] = tr.snapshot()
    fields[f"goodput_{name}"] = gp.snapshot()
    fields[f"numerics_{name}"] = get_monitor().snapshot()
    fields[f"sdc_{name}"] = sdc_monitor().snapshot()
    fields[f"memory_{name}"] = mm.snapshot()
    fields[f"audit_{name}"] = audit_rt.snapshot()
    print(json.dumps(rec), flush=True)


def _run_leg(name, timeout, args=(), extra_env=None):
    """Run one leg in a watchdog-guarded subprocess; parse its JSON line.
    Never raises: returns {"ok": False, "error": ...} on any failure."""
    cmd = [sys.executable, os.path.abspath(__file__), "--leg", name,
           *map(str, args)]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout, cwd=_HERE,
                             env={**os.environ, **(extra_env or {})})
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"watchdog timeout after {timeout}s",
                "timeout": True}
    except Exception:
        return {"ok": False,
                "error": _error_tail(traceback.format_exc(limit=5))}
    for line in reversed(out.stdout.strip().splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except Exception:
                break
    tail = (out.stderr.strip().splitlines() or ["no output"])[-1][:400]
    return {"ok": False, "error": f"leg rc={out.returncode}: {tail}",
            "oom": _is_oom_str(out.stderr)}


def _gpt_ladder_start():
    """Persisted known-good GPT config (committed cache file; updated on
    a successful local run). Avoids burning a ~100 s compile every round
    to rediscover that (16, no-remat) OOMs a 16G chip."""
    try:
        with open(_GPT_CACHE) as f:
            c = json.load(f)
        return int(c["batch"]), bool(c["recompute"])
    except Exception:
        return 8, False


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--leg":
        name = sys.argv[2]
        batch = int(sys.argv[3]) if len(sys.argv) > 3 else 0
        recompute = bool(int(sys.argv[4])) if len(sys.argv) > 4 else True
        _leg_main(name, batch, recompute)
        return

    t_start = time.time()
    errors: dict = {}
    result: dict = {
        "metric": "resnet50_cifar10_train_throughput",
        "value": None,
        "unit": "images/sec",
        "vs_baseline": None,
        "device_kind": None,
        # master-weight precision of the headline training legs (the
        # gpt AMP leg casts compute to bf16 under O2 but keeps fp32
        # masters); serving precision lives on bench_serve records
        "precision": "fp32",
    }

    # parent-side telemetry: cheap (the parent never touches the device —
    # its snapshot proves that: 0 steps, 0 compiles, no device memory),
    # but it carries pid/health onto every emitted record including the
    # tpu_unreachable fast-fail, where the leg snapshots never happen
    from paddle_tpu.observability import get_telemetry
    from paddle_tpu.observability.trace import get_tracer
    from paddle_tpu.observability.goodput import get_goodput
    from paddle_tpu.observability.numerics import get_monitor
    from paddle_tpu.observability.sdc import get_monitor as sdc_monitor
    from paddle_tpu.observability.memory import get_memory_monitor
    from paddle_tpu.tools.audit import runtime as audit_rt
    tel = get_telemetry().enable()
    tr = get_tracer().enable()
    gp = get_goodput().enable()
    mm = get_memory_monitor().enable()
    audit_rt.enable()

    def remaining():
        return BUDGET_SEC - (time.time() - t_start)

    def emit():
        # partial emission: the driver keeps the tail of stdout, so the
        # last printed line always carries everything measured so far
        if errors:
            result["errors"] = dict(errors)
        else:
            result.pop("errors", None)
        result["telemetry_driver"] = tel.snapshot()
        result["telemetry_cluster"] = _cluster_snapshot()
        # every printed record carries a trace block — including the
        # tpu_unreachable fast-fail, where only the CPU leg ran
        result["trace"] = tr.snapshot()
        # …and the goodput/numerics pair rides the same guarantee: the
        # driver-side decomposition (mostly badput — the parent never
        # trains) plus the anomaly ledger, best-effort by contract
        try:
            result["goodput"] = gp.snapshot()
            result["numerics"] = get_monitor().snapshot()
            # …and the SDC sentry block: fingerprint reads, votes, and
            # divergence verdicts — the all-zero disabled snapshot when
            # the sentry never armed, so it rides every record too
            result["sdc"] = sdc_monitor().snapshot()
            # …and the memory block: fit verdicts + watermark summary,
            # {} stats on the tpu_unreachable CPU fast-fail
            result["memory"] = mm.snapshot()
            # …and the audit block: the driver never compiles, so this
            # stays empty here; per-leg audit_{name} blocks carry the
            # findings booked inside the leg subprocesses
            result["audit"] = audit_rt.snapshot()
            # …and the supervision block: restart counts, store
            # promotions and replay badput from the most recent
            # Supervisor in this process — the all-zero default when
            # nothing was supervised, so it rides every record
            # including the tpu_unreachable fast-fail
            from paddle_tpu.distributed.supervisor import \
                supervision_snapshot
            result["supervision"] = supervision_snapshot()
        except Exception:
            pass
        print(json.dumps(result), flush=True)

    def merge(rec, stage):
        for k, v in (rec.get("fields") or {}).items():
            if v is not None or k not in result:
                result[k] = v
        if rec.get("ok"):
            errors.pop(stage, None)
        elif rec.get("error"):
            errors[stage] = rec["error"]
        emit()
        return bool(rec.get("ok"))

    # --- CPU leg first: the host-side dispatch microbench never needs
    # the tunnel, so its numbers land even if every TPU leg dies.
    def run_eager():
        out = subprocess.run(
            [sys.executable, os.path.join(_HERE, "bench_eager.py")],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        if out.returncode != 0:
            raise RuntimeError(out.stderr.strip().splitlines()[-1][:200]
                               if out.stderr.strip()
                               else f"bench_eager rc={out.returncode}")
        return json.loads(out.stdout.strip().splitlines()[-1])

    try:
        eager = run_eager()
        result["eager_dispatch_us_per_op"] = {
            k: eager[k] for k in ("raw_jax", "tape_off", "tape_on",
                                  "jit_chain", "tape_overhead_ratio")
            if k in eager}
        # the CPU leg's trace block: analytic MFU against the nominal
        # cpu peak — present even when every TPU leg dies
        result["trace_eager"] = eager.get("trace")
    except Exception:
        errors["eager_dispatch"] = _error_tail(traceback.format_exc(limit=5))
    emit()

    # --- canary: is the tunnel alive? A *fast* canary failure (import
    # error, refused connection) gets a watchdogged retry — the tunnel
    # has been observed taking >2.5 min just to hand out
    # jax.local_devices(), so transients deserve a second look. A canary
    # *watchdog timeout* is different: the process sat the full budget
    # with a hung tunnel, and stacking a 420 s retry plus 600-900 s
    # heavy legs on top is exactly the rc=124 driver kill of r05.
    # Timeout => no retry, no heavy legs, one fast-fail record.
    rec = _run_leg("canary", LEG_TIMEOUT["canary"])
    canary_ok = merge(rec, "canary")
    canary_hung = bool(rec.get("timeout"))
    if (not canary_ok and not canary_hung
            and remaining() > LEG_TIMEOUT["canary_retry"] + 120):
        time.sleep(5 if SMOKE else 30)
        rec = _run_leg("canary", LEG_TIMEOUT["canary_retry"])
        canary_ok = merge(rec, "canary")
        canary_hung = bool(rec.get("timeout"))

    def leg_budget(name):
        t = min(LEG_TIMEOUT[name], max(remaining() - 60, 0))
        return t if t >= 180 or SMOKE else 0

    def try_leg(name, stage=None, args=()):
        t = leg_budget(name)
        if t <= 0:
            errors[stage or name] = "skipped: bench budget exhausted"
            emit()
            return None
        rec = _run_leg(name, t, args=args)
        merge(rec, stage or name)
        return rec

    # --- heavy legs. On a dead canary still attempt the two that
    # matter most (resnet = headline value, gpt = MFU target) — the
    # canary may have failed on a transient while the tunnel recovers.
    if canary_ok:
        # headline leg gets a budget-gated second attempt: a transient
        # tunnel blip must not cost the round's "value" (the old code
        # had attempts=5; one retry preserves that invariant cheaply)
        rec = try_leg("resnet")
        if rec is not None and not rec.get("ok"):
            try_leg("resnet")

        # GPT ladder, fastest-first; start at the persisted known-good
        # rung, descend on OOM/timeout, and on success CLIMB one rung
        # back up (budget permitting) so a transient OOM in a past
        # round cannot pin the cache to a slow config forever. One
        # config per subprocess (two 345M step builds in one process
        # OOM the 16G chip).
        rungs = [(8, False), (8, True), (4, True), (2, True)]
        start = _gpt_ladder_start()
        if start not in rungs:
            rungs.insert(0, start)  # hand-edited cache: trust it first
        i0 = rungs.index(start)
        measured: dict = {}  # cfg -> tokens/sec
        i = i0
        while i < len(rungs):
            b, rc = rungs[i]
            rec = try_leg("gpt", stage=f"gpt345m_b{b}_rc{int(rc)}",
                          args=(b, int(rc)))
            if rec is None:
                break
            if rec.get("ok"):
                measured[rungs[i]] = (rec.get("fields") or {}).get(
                    "gpt345m_tokens_per_sec") or 0
                break
            if not rec.get("oom") and not rec.get("timeout"):
                break  # real error: retrying a smaller batch won't help
            i += 1
        if measured and i == i0 and i0 > 0:
            t = leg_budget("gpt")
            if t > 0:
                b, rc = rungs[i0 - 1]
                up = _run_leg("gpt", t, args=(b, int(rc)))
                tps = (up.get("fields") or {}).get("gpt345m_tokens_per_sec") \
                    if up.get("ok") else None
                if tps and tps > max(measured.values()):
                    measured[rungs[i0 - 1]] = tps
                    merge(up, f"gpt345m_b{b}_rc{int(rc)}")
                # a failed climb is expected exploration, not an error
        if measured:
            for b, rc in rungs:  # OOM rungs above a success aren't errors
                errors.pop(f"gpt345m_b{b}_rc{int(rc)}", None)
            emit()
            best_cfg = max(measured, key=measured.get)
            try:
                with open(_GPT_CACHE, "w") as f:
                    json.dump({"batch": best_cfg[0],
                               "recompute": best_cfg[1]}, f)
            except OSError:
                pass

        # new-kernel evidence legs before bert (bert has 3 prior
        # driver captures already; packed/ring/kernels have none)
        try_leg("packed")
        try_leg("ring")
        try_leg("kernels")

        def bert_ladder():
            for b in (32, 16, 8):
                rec = try_leg("bert", stage=f"bert_b{b}", args=(b,))
                if rec is None or rec.get("ok") or not rec.get("oom"):
                    if rec is not None and rec.get("ok"):
                        for bb in (32, 16, 8):
                            errors.pop(f"bert_b{bb}", None)
                    return
        bert_ladder()
    elif canary_hung:
        # the canary burned its whole watchdog with the tunnel hung:
        # the heavy legs would do the same (their compiles alone exceed
        # the canary's matmul). Emit the fast-fail record and stop —
        # total wall stays ~eager + one canary budget instead of
        # 300 + 420 + 600+ s of stacked watchdogs.
        result["tpu_unreachable"] = True
        errors["tpu"] = ("canary watchdog timeout — tunnel unreachable; "
                         "heavy legs skipped (fast-fail)")
    else:
        # canary failed fast (not a hang) — the tunnel may be recovering
        # from a transient, so still attempt the two headline legs with
        # watchdogs; worst case they burn their timeouts and we report.
        try_leg("resnet")
        b, rc = _gpt_ladder_start()
        try_leg("gpt", stage=f"gpt345m_b{b}_rc{int(rc)}", args=(b, int(rc)))

    result["bench_wall_sec"] = round(time.time() - t_start, 1)
    # rc=0 iff at least one throughput number was measured — any leg's
    ok = any(result.get(k) is not None for k in (
        "value", "gpt345m_tokens_per_sec", "bert_base_seq_per_sec",
        "ring_attn_fwdbwd_ms", "packed_varlen_tokens_per_sec"))
    emit()
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
