"""Benchmark entry (driver-run on real TPU hardware).

Measures two BASELINE.md configs on a single chip:
 - configs[0]: ResNet-50 training throughput, CIFAR-10-shaped data
   (batch 256, 3x32x32), images/sec.
 - configs[3]-class: GPT-345M causal-LM training, seq 1024, bf16 AMP,
   tokens/sec/chip + MFU — the transformer fast path the framework is for.

Each train step (forward + backward + optimizer update) is ONE jitted XLA
program with bf16 AMP. MFU comes from XLA's own cost analysis vs the chip's
public bf16 peak.

Robustness (BENCH_r02 post-mortem: a refused tunnel connection at
param-init time produced rc=1 and zero signal): every device-touching
stage runs under bounded retry-with-backoff, and the script ALWAYS prints
its one JSON line — with partial fields (device_kind, compile time,
cost-analysis FLOPs, error tails) when a stage could not complete. rc=0
iff at least one throughput number was measured.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline", ...}.
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

SMOKE = bool(os.environ.get("BENCH_SMOKE"))  # tiny-shape CI structure check
RESNET_BATCH = 8 if SMOKE else 256
GPT_SEQ = 64 if SMOKE else 1024
BERT_SEQ = 128
WARMUP = 1 if SMOKE else 5
ITERS = 2 if SMOKE else 15       # steps per timed block
BLOCKS = 1 if SMOKE else 3       # timed blocks -> min/median/max spread
RETRIES = 1 if SMOKE else 5
BACKOFF = (5, 10, 20, 40, 60)  # seconds between attempts

# Driver-captured r03 numbers (BENCH_r03.json, 2026-07-30) — the
# reproducible baseline this build is measured against. vs_baseline is
# measured/THIS, so >1.0 means faster than the last driver capture.
_DRIVER_BASELINE = {
    "resnet50_img_per_sec": 152580.22,
    "gpt345m_tokens_per_sec": 17176.5,
    "bert_base_seq_per_sec": 809.1,
}

# bf16 peak FLOP/s per chip by device kind (public spec sheets)
_PEAK = {
    "TPU v4": 275e12, "TPU v5": 459e12, "TPU v5p": 459e12,
    "TPU v5e": 197e12, "TPU v5 lite": 197e12, "TPU v6e": 918e12,
    "TPU v6 lite": 918e12, "TPU v3": 123e12, "TPU v2": 45e12,
}


def _error_tail(tb: str) -> str:
    """Last *informative* line of a traceback: jax/XLA errors often end
    with decorative ===/--- rules (the BENCH_r03 gpt error recorded just
    '==========' before this existed)."""
    lines = [ln.strip() for ln in tb.strip().splitlines()]
    for ln in reversed(lines):
        if ln and any(c.isalnum() for c in ln):
            return ln[:400]
    return (lines[-1] if lines else "")[:400]


def _is_oom(e: Exception) -> bool:
    s = str(e)
    return any(t in s for t in (
        "RESOURCE_EXHAUSTED", "Resource exhausted", "out of memory",
        "Out of memory", "OOM", "Allocation failure",
        "exceeds the memory capacity", "exceeds available memory"))


def _retry(stage_name, fn, errors, attempts=RETRIES):
    """Run fn() with bounded retry-with-backoff. Returns result or None;
    records the last error tail in errors[stage_name]."""
    for attempt in range(attempts):
        try:
            out = fn()
            errors.pop(stage_name, None)  # earlier attempts' noise
            return out
        except Exception:
            errors[stage_name] = _error_tail(traceback.format_exc(limit=20))
            if attempt < attempts - 1:
                time.sleep(BACKOFF[min(attempt, len(BACKOFF) - 1)])
    return None


def _honor_cpu_override():
    """The environment's sitecustomize force-registers the TPU-tunnel
    backend via jax.config (overriding the JAX_PLATFORMS env var); when
    the caller explicitly asked for cpu, re-assert it before any backend
    initializes."""
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass


def _flops_per_step(compiled):
    """Model FLOPs per step from XLA's own cost analysis (None if n/a)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0)) or None
    except Exception:
        return None


def _memory_report(compiled):
    """Per-step HBM footprint from XLA's memory analysis (the L1
    peak-memory reporting: arguments = resident state, temp = activation
    working set)."""
    try:
        ma = compiled.memory_analysis()
        return {
            "argument_bytes": int(getattr(ma, "argument_size_in_bytes", 0)),
            "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
            "output_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
        }
    except Exception:
        return None


def _peak_flops(device_kind):
    kind = (device_kind or "").lower()
    # longest prefix wins ("TPU v5 lite" must not match "TPU v5")
    for k in sorted(_PEAK, key=len, reverse=True):
        if kind.startswith(k.lower()):
            return _PEAK[k]
    return None


def _fetch_scalar(out):
    """HOST READBACK of the step's loss — the only trustworthy fence.
    On the remote-tunnel backend ``block_until_ready`` can return without
    waiting and identical repeated executions can be served from a
    cache; threading state forward + pulling a scalar defeats both
    (measured r04: a broken fence reported 5.76ms for a 17-TFLOP step)."""
    import numpy as np
    return float(np.asarray(out[0]))


_FENCE_STATE = {}


def _fence_cost():
    """Round-trip latency of one scalar readback, measured on a FRESH
    tiny computation each call (re-fetching an already-fetched jax.Array
    returns its cached host value in microseconds, and repeating an
    identical execution can be served from the tunnel's cache — both
    would fake a near-zero fence)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    if "fn" not in _FENCE_STATE:
        _FENCE_STATE["fn"] = jax.jit(lambda s: s * 1.000001 + 1e-9)
        _FENCE_STATE["x"] = jnp.float32(1.234)
        _FENCE_STATE["x"] = _FENCE_STATE["fn"](_FENCE_STATE["x"])
        float(np.asarray(_FENCE_STATE["x"]))  # compile + warm
    costs = []
    for _ in range(2):
        t0 = time.perf_counter()
        _FENCE_STATE["x"] = _FENCE_STATE["fn"](_FENCE_STATE["x"])
        float(np.asarray(_FENCE_STATE["x"]))
        costs.append(time.perf_counter() - t0)
    return min(costs)


def _time_compiled(compiled, args, n_state):
    """Warmup + BLOCKS timed blocks of ITERS steps, each fenced by a
    loss readback whose latency is measured and subtracted. The step's
    first n_state outputs feed back as its first n_state inputs (fresh
    buffers every call). Returns (per_step_seconds_list, final_out)."""
    state = list(args[:n_state])
    rest = list(args[n_state:])
    out = None
    for _ in range(WARMUP):
        out = compiled(*state, *rest)
        state = list(out[1:1 + n_state])
    _fetch_scalar(out)
    times = []
    for _ in range(BLOCKS):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            out = compiled(*state, *rest)
            state = list(out[1:1 + n_state])
        _fetch_scalar(out)
        dt = time.perf_counter() - t0
        fence = _fence_cost()
        times.append(max(dt - fence, 1e-9) / ITERS)
    return times, out


def _spread_ms(times):
    s = sorted(t * 1000 for t in times)
    return {"min": round(s[0], 2), "median": round(s[len(s) // 2], 2),
            "max": round(s[-1], 2)}


def bench_resnet(result, errors):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.jit.api import functional_call
    from paddle_tpu.tensor import Tensor

    pt.seed(0)
    net = pt.vision.models.resnet50(num_classes=10)
    pt.amp.decorate(net, level="O2", dtype="bfloat16")
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=net.parameters(),
                                multi_precision=True)
    params = {k: p._data for k, p in net.named_parameters()}
    buffers = {k: b._data for k, b in net.named_buffers()}
    opt_state = opt.init_state_tree(params)
    fwd = getattr(net, "_orig_forward", net.forward)

    def train_step(params, buffers, opt_state, x, y):
        def loss_of(p):
            out, new_buffers = functional_call(
                net, p, buffers, (Tensor(x),), training=True, forward_fn=fwd)
            logits = out._data.astype(jnp.float32)
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
            return loss, new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_tree(params, grads,
                                                       opt_state)
        return loss, new_params, new_buffers, new_opt

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(RESNET_BATCH, 3, 32, 32)
                    .astype(np.float32)).astype(jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 10, RESNET_BATCH).astype(np.int32))

    t0 = time.perf_counter()
    compiled = step.lower(params, buffers, opt_state, x, y).compile()
    result["resnet50_compile_sec"] = round(time.perf_counter() - t0, 2)
    flops = _flops_per_step(compiled)
    result["resnet50_flops_per_step"] = flops
    result["resnet50_memory"] = _memory_report(compiled)

    times, _ = _time_compiled(compiled, (params, buffers, opt_state, x, y),
                              3)
    result["resnet50_step_ms"] = _spread_ms(times)
    step = sorted(times)[len(times) // 2]
    ips = RESNET_BATCH / step
    result["value"] = round(ips, 2)
    result["vs_baseline"] = round(
        ips / _DRIVER_BASELINE["resnet50_img_per_sec"], 3)
    peak = _peak_flops(result.get("device_kind"))
    if flops and peak:
        result["mfu"] = round(flops / step / peak, 4)
    return ips


def bench_gpt(result, errors, batch, recompute=True):
    """GPT-345M-class train step (bf16, seq 1024) — tokens/sec/chip + MFU."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.jit.api import functional_call
    from paddle_tpu.tensor import Tensor
    from paddle_tpu.incubate.models import (GPTForCausalLM,
                                            GPTPretrainingCriterion,
                                            gpt_345m)

    pt.seed(0)
    if SMOKE:
        from paddle_tpu.incubate.models import gpt_tiny
        cfg = gpt_tiny(tensor_parallel=False, use_recompute=recompute)
    else:
        cfg = gpt_345m(tensor_parallel=False, use_recompute=recompute,
                       max_position_embeddings=GPT_SEQ)
    result["gpt345m_recompute"] = recompute
    model = GPTForCausalLM(cfg)
    pt.amp.decorate(model, level="O2", dtype="bfloat16")
    crit = GPTPretrainingCriterion()
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True)
    params = {k: p._data for k, p in model.named_parameters()}
    buffers = {k: b._data for k, b in model.named_buffers()}
    opt_state = opt.init_state_tree(params)
    fwd = getattr(model, "_orig_forward", model.forward)
    n_params = sum(int(np.prod(p.shape)) for p in params.values())
    result["gpt345m_n_params"] = n_params

    def train_step(params, buffers, opt_state, ids, labels):
        def loss_of(p):
            out, new_buffers = functional_call(
                model, p, buffers, (Tensor(ids),), training=True,
                forward_fn=fwd)
            loss = crit(out, Tensor(labels))
            return loss._data.astype(jnp.float32), new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_tree(params, grads,
                                                       opt_state)
        return loss, new_params, new_buffers, new_opt

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, GPT_SEQ))
                      .astype(np.int32))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, GPT_SEQ))
                         .astype(np.int32))

    t0 = time.perf_counter()
    compiled = step.lower(params, buffers, opt_state, ids, labels).compile()
    result["gpt345m_compile_sec"] = round(time.perf_counter() - t0, 2)
    flops = _flops_per_step(compiled)
    result["gpt345m_flops_per_step"] = flops
    result["gpt345m_memory"] = _memory_report(compiled)

    times, _ = _time_compiled(compiled,
                              (params, buffers, opt_state, ids, labels), 3)
    result["gpt345m_step_ms"] = _spread_ms(times)
    step = sorted(times)[len(times) // 2]
    tps = batch * GPT_SEQ / step
    result["gpt345m_tokens_per_sec"] = round(tps, 1)
    result["gpt345m_vs_baseline"] = round(
        tps / _DRIVER_BASELINE["gpt345m_tokens_per_sec"], 3)
    result["gpt345m_batch"] = batch
    result["gpt345m_seq"] = GPT_SEQ
    peak = _peak_flops(result.get("device_kind"))
    if flops and peak:
        # hardware utilization per XLA's cost analysis. Caveat: custom
        # Pallas kernels (flash attention) report no flops to XLA, so
        # this undercounts when the flash path is active.
        result["gpt345m_mfu"] = round(flops / step / peak, 4)
    if peak:
        # standard analytic MFU: 6N per token fwd+bwd + causal attention
        # 6*L*S*H (recomputed FLOPs deliberately NOT counted — the
        # convention used by the public scaling literature)
        per_token = (6 * n_params
                     + 6 * cfg.num_layers * GPT_SEQ * cfg.hidden_size)
        result["gpt345m_mfu_model"] = round(tps * per_token / peak, 4)
    return tps


def bench_bert(result, errors, batch):
    """BERT-base SST-2-style finetune step (config[1]): seq/sec via the
    compiled (to_static-equivalent) path, bf16 AMP."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.jit.api import functional_call
    from paddle_tpu.tensor import Tensor
    from paddle_tpu.incubate.models import (BertForSequenceClassification,
                                            bert_base, bert_tiny)

    pt.seed(0)
    cfg = bert_tiny() if SMOKE else bert_base()
    model = BertForSequenceClassification(cfg, num_classes=2)
    pt.amp.decorate(model, level="O2", dtype="bfloat16")
    opt = pt.optimizer.AdamW(learning_rate=2e-5,
                             parameters=model.parameters(),
                             multi_precision=True)
    params = {k: p._data for k, p in model.named_parameters()}
    buffers = {k: b._data for k, b in model.named_buffers()}
    opt_state = opt.init_state_tree(params)
    fwd = getattr(model, "_orig_forward", model.forward)
    seq = 32 if SMOKE else BERT_SEQ

    def train_step(params, buffers, opt_state, ids, y):
        def loss_of(p):
            out, new_buffers = functional_call(
                model, p, buffers, (Tensor(ids),), training=True,
                forward_fn=fwd)
            logits = out._data.astype(jnp.float32)
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
            return loss, new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_tree(params, grads,
                                                       opt_state)
        return loss, new_params, new_buffers, new_opt

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq))
                      .astype(np.int32))
    y = jnp.asarray(rng.randint(0, 2, batch).astype(np.int32))

    t0 = time.perf_counter()
    compiled = step.lower(params, buffers, opt_state, ids, y).compile()
    result["bert_base_compile_sec"] = round(time.perf_counter() - t0, 2)
    flops = _flops_per_step(compiled)
    result["bert_base_flops_per_step"] = flops
    result["bert_base_memory"] = _memory_report(compiled)

    times, _ = _time_compiled(compiled, (params, buffers, opt_state, ids, y),
                              3)
    result["bert_base_step_ms"] = _spread_ms(times)
    step = sorted(times)[len(times) // 2]
    sps = batch / step
    result["bert_base_seq_per_sec"] = round(sps, 1)
    result["bert_base_vs_baseline"] = round(
        sps / _DRIVER_BASELINE["bert_base_seq_per_sec"], 3)
    result["bert_base_batch"] = batch
    result["bert_base_seq_len"] = seq
    peak = _peak_flops(result.get("device_kind"))
    if flops and peak:
        result["bert_base_mfu"] = round(flops / step / peak, 4)
    return sps


def bench_ring(result, errors):
    """Ring-attention leg: the Pallas flash kernel driven through the
    shard_map ring schedule on the real chip (1-device mesh still
    exercises the kernel lowering + collective plumbing), S=8192 —
    the long-context path BENCH r03 never touched.

    Also records the compiled program's temp bytes: ring attention's
    working set must stay O(S_local * block) — far below the O(S^2)
    logits buffer a dense attention would need at this length."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.distributed.fleet.meta_parallel.sequence_parallel \
        import ring_attention

    B, H, S, D = 1, 16, 512 if SMOKE else 8192, 64
    mesh = Mesh(np.array(jax.devices()[:1]), ("sep",))
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)).astype(
        jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)).astype(
        jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)).astype(
        jnp.bfloat16)

    def fwd_bwd(q, k, v):
        def loss(q):
            out = jax.shard_map(
                lambda a, b, c: ring_attention(a, b, c, causal=True),
                mesh=mesh, in_specs=(P(None, None, "sep"),) * 3,
                out_specs=P(None, None, "sep"))(q, k, v)
            return jnp.sum(out.astype(jnp.float32)), out
        (s, out), dq = jax.value_and_grad(loss, has_aux=True)(q)
        return s, dq

    step = jax.jit(fwd_bwd)
    t0 = time.perf_counter()
    compiled = step.lower(q, k, v).compile()
    result["ring_attn_compile_sec"] = round(time.perf_counter() - t0, 2)
    result["ring_attn_memory"] = _memory_report(compiled)

    def run(qq):
        s, dq = compiled(qq, k, v)
        return s, (dq.astype(jnp.float32) * 1e-3).astype(qq.dtype)

    s, qq = run(q)
    float(np.asarray(s))
    iters = 2 if SMOKE else 8
    t0 = time.perf_counter()
    for _ in range(iters):
        s, qq = run(qq)
    float(np.asarray(s))
    dt = time.perf_counter() - t0
    fence = _fence_cost()
    ms = max(dt - fence, 1e-9) / iters * 1000
    result["ring_attn_fwdbwd_ms"] = round(ms, 2)
    result["ring_attn_seq"] = S
    # sanity: the temp working set must be far below the O(S^2) dense
    # logits buffer (B*H*S*S bf16)
    mem = result.get("ring_attn_memory") or {}
    dense_logits_bytes = 2 * B * H * S * S
    result["ring_attn_temp_vs_dense_logits"] = round(
        mem.get("temp_bytes", 0) / dense_logits_bytes, 4) \
        if mem.get("temp_bytes") else None
    return ms


def main():
    errors: dict = {}
    result: dict = {
        "metric": "resnet50_cifar10_train_throughput",
        "value": None,
        "unit": "images/sec",
        "vs_baseline": None,
    }

    _honor_cpu_override()

    def probe():
        # subprocess probe with a hard timeout: a HANGING tunnel (observed
        # in round 3: jax.devices() blocked >6 min) must not stall the
        # whole bench past the driver's budget. Only after the probe
        # succeeds do we initialize jax in-process.
        import subprocess
        code = ("import os, jax\n"
                "if os.environ.get('JAX_PLATFORMS','').strip() == 'cpu':\n"
                "    jax.config.update('jax_platforms', 'cpu')\n"
                "print(jax.local_devices()[0].device_kind)\n")
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=60 if SMOKE else 120)
        if out.returncode != 0:
            raise RuntimeError(out.stderr.strip().splitlines()[-1][:400]
                               if out.stderr.strip() else "probe failed")
        return out.stdout.strip().splitlines()[-1]

    kind = _retry("device_probe", probe, errors, attempts=3)
    result["device_kind"] = kind

    if kind is not None:
        _retry("resnet50", lambda: bench_resnet(result, errors), errors)

        def run_gpt():
            # ladder: no-remat first (fewer FLOPs when it fits), then
            # remat, then halve the batch; non-OOM errors retry via
            # _retry. First-fit is NOT always fastest (on v5e-lite 16G,
            # (8, no-remat) beats (16, remat)), so keep measuring until
            # two configs succeed and report the better one.
            ladder = ((16, False), (8, False), (16, True), (8, True),
                      (4, True), (2, True))
            best, successes = None, 0
            for b, rc in ladder:
                trial = dict(result)
                try:
                    bench_gpt(trial, errors, b, recompute=rc)
                except Exception as e:
                    errors[f"gpt345m_b{b}_rc{int(rc)}"] = _error_tail(
                        traceback.format_exc(limit=20))
                    if successes > 0:
                        break  # keep the measured config, don't discard it
                    if not _is_oom(e) or (b, rc) == ladder[-1]:
                        raise
                    continue
                successes += 1
                if best is None or (trial.get("gpt345m_tokens_per_sec", 0)
                                    > best.get("gpt345m_tokens_per_sec", 0)):
                    best = trial
                if successes >= 2:
                    break
            if best is not None:
                result.update(best)
                # successful descent: earlier rungs' OOMs aren't errors
                for bb, rr in ladder:
                    errors.pop(f"gpt345m_b{bb}_rc{int(rr)}", None)
            return best

        _retry("gpt345m", run_gpt, errors)

        def run_bert():
            ladder = (32, 16, 8)
            for b in ladder:
                try:
                    return bench_bert(result, errors, b)
                except Exception as e:
                    if not _is_oom(e) or b == ladder[-1]:
                        raise
            return None

        _retry("bert_base", run_bert, errors)
        _retry("ring_attn", lambda: bench_ring(result, errors), errors,
               attempts=2)

    def run_eager_bench():
        # host-side dispatch microbench (bench_eager.py) in a CPU-forced
        # subprocess; its one JSON line rides along in the record
        import subprocess
        here = os.path.dirname(os.path.abspath(__file__))
        out = subprocess.run(
            [sys.executable, os.path.join(here, "bench_eager.py")],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        if out.returncode != 0:
            raise RuntimeError(out.stderr.strip().splitlines()[-1][:200]
                               if out.stderr.strip()
                               else f"bench_eager rc={out.returncode}")
        return json.loads(out.stdout.strip().splitlines()[-1])

    eager = _retry("eager_dispatch", run_eager_bench, errors, attempts=1)
    if eager:
        result["eager_dispatch_us_per_op"] = {
            k: eager[k] for k in ("raw_jax", "tape_off", "tape_on",
                                  "jit_chain", "tape_overhead_ratio")
            if k in eager}

    if errors:
        result["errors"] = errors
    ok = (result["value"] is not None or
          result.get("gpt345m_tokens_per_sec") is not None)
    print(json.dumps(result))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
