"""Eager (dygraph) per-op dispatch microbenchmark.

The reference spends an entire codegen subsystem keeping eager dispatch
cheap (``paddle/fluid/eager/auto_code_generator/``, SURVEY §3.1). Our
dygraph tape instead pays one ``jax.vjp`` trace per recorded op. This
script puts a number on that: per-op wall time for

 - ``raw_jax``      : bare jax.numpy dispatch (the floor),
 - ``tape_off``     : paddle_tpu Tensor op with stop_gradient=True
                      (funnel overhead, no autograd),
 - ``tape_on``      : same op recorded on the tape (jax.vjp per op),
 - ``jit_chain``    : the whole chain as one jitted program (per-op cost
                      amortized — the designed fast path for hot loops).

Host-side dispatch cost: runs on the CPU backend (never the TPU tunnel).
Prints ONE json line.
"""
from __future__ import annotations

import json
import os
import time

os.environ["JAX_PLATFORMS"] = "cpu"

N_OPS = 200
REPEATS = 20
SHAPE = (64, 64)


def _bench_all(variants):
    """Interleaved min-of-REPEATS over all variants: the bench box is a
    single noisy core, and measuring variants back-to-back lets load
    drift fake a high tape/raw ratio. One round measures every variant
    once; the per-variant min over rounds drops the noise floor of each
    independently."""
    best = {name: float("inf") for name, _, _ in variants}
    for name, fn, block in variants:  # untimed warmup
        block(fn())
    for _ in range(REPEATS):
        for name, fn, block in variants:
            t0 = time.perf_counter()
            block(fn())
            dt = time.perf_counter() - t0
            if dt < best[name]:
                best[name] = dt
    return {name: best[name] / N_OPS for name, _, _ in variants}


def main():
    import jax
    import jax.numpy as jnp
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt

    x = jnp.ones(SHAPE, jnp.float32)
    y = jnp.full(SHAPE, 0.5, jnp.float32)

    def raw_jax():
        z = x
        for _ in range(N_OPS):
            z = z * y + y
        return z

    tx = pt.to_tensor(x)
    ty = pt.to_tensor(y)
    tx.stop_gradient = True
    ty.stop_gradient = True

    def tape_off():
        z = tx
        for _ in range(N_OPS):
            z = z * ty + ty
        return z

    gx = pt.to_tensor(x)
    gy = pt.to_tensor(y)
    gx.stop_gradient = False
    gy.stop_gradient = False

    def tape_on():
        z = gx
        for _ in range(N_OPS):
            z = z * gy + gy
        return z

    from paddle_tpu.observability import get_telemetry
    tel = get_telemetry().enable()

    jitted = jax.jit(raw_jax)
    jitted()  # compile outside the timing

    block_jax = lambda z: jax.block_until_ready(z)
    block_pt = lambda z: jax.block_until_ready(z._data)

    us = _bench_all([
        ("raw_jax", raw_jax, block_jax),
        ("tape_off", tape_off, block_pt),
        ("tape_on", tape_on, block_pt),
        ("jit_chain", jitted, block_jax),
    ])
    res = {
        "metric": "eager_dispatch_overhead",
        "unit": "us/op",
        **{k: round(v * 1e6, 2) for k, v in us.items()},
        "n_ops": N_OPS,
        "shape": list(SHAPE),
    }
    # each op here is mul+add fused in one funnel call; normalize names
    res["tape_overhead_ratio"] = round(res["tape_on"] / res["raw_jax"], 2) \
        if res["raw_jax"] else None
    res["value"] = res["tape_on"]
    res["telemetry"] = tel.snapshot()
    try:
        from paddle_tpu.observability import cluster_snapshot
        res["telemetry_cluster"] = cluster_snapshot(
            url=os.environ.get("PT_AGGREGATOR_URL") or None)
    except Exception as e:  # snapshot is best-effort by contract
        res["telemetry_cluster"] = {"error": str(e)[:200]}
    print(json.dumps(res))


if __name__ == "__main__":
    main()
