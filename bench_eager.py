"""Eager (dygraph) per-op dispatch microbenchmark.

The reference spends an entire codegen subsystem keeping eager dispatch
cheap (``paddle/fluid/eager/auto_code_generator/``, SURVEY §3.1). Our
dygraph tape instead pays one ``jax.vjp`` trace per recorded op. This
script puts a number on that: per-op wall time for

 - ``raw_jax``      : bare jax.numpy dispatch (the floor),
 - ``tape_off``     : paddle_tpu Tensor op with stop_gradient=True
                      (funnel overhead, no autograd),
 - ``tape_on``      : same op recorded on the tape (jax.vjp per op),
 - ``captured_step``: the chain behind ``jit.capture_step`` — one cached
                      jitted program plus the capture dispatch layer
                      (signature hash, state writeback),
 - ``jit_chain``    : the whole chain as one jitted program (per-op cost
                      amortized — the floor capture aims for).

The record also carries a ``capture`` block: a 10-step captured MLP
train run asserting the trace-and-cache contract (1 compile, >=9 cache
hits, recompile sentinel quiet) — and a ``numerics_contract`` block
asserting the monitored-capture contract: folding the numerics
sentinel into the captured step keeps exactly one compile, changes no
math (bit-identical loss sequence), stays quiet on healthy training,
and costs < 3% wall overhead per step.  The ``memory_contract`` block
holds the memory monitor to the same bar: footprint harvested at the
one compile, census attributing parameter bytes, and < 1% step
overhead with watermark sampling on every step.

Host-side dispatch cost: runs on the CPU backend (never the TPU tunnel).
Prints ONE json line.
"""
from __future__ import annotations

import json
import os
import time

os.environ["JAX_PLATFORMS"] = "cpu"

N_OPS = 200
REPEATS = 20
SHAPE = (64, 64)


def _bench_all(variants):
    """Interleaved min-of-REPEATS over all variants: the bench box is a
    single noisy core, and measuring variants back-to-back lets load
    drift fake a high tape/raw ratio. One round measures every variant
    once; the per-variant min over rounds drops the noise floor of each
    independently."""
    best = {name: float("inf") for name, _, _ in variants}
    for name, fn, block in variants:  # untimed warmup
        block(fn())
    for _ in range(REPEATS):
        for name, fn, block in variants:
            # one untimed call first: the runtime defers buffer cleanup
            # from the PREVIOUS variant's op storm into the next
            # dispatch, which would bill ~100us of teardown to whoever
            # runs after tape_on; this absorbs it so every slot times
            # its own steady state
            block(fn())
            t0 = time.perf_counter()
            block(fn())
            dt = time.perf_counter() - t0
            if dt < best[name]:
                best[name] = dt
    return {name: best[name] / N_OPS for name, _, _ in variants}


def _capture_contract(pt):
    """10-step captured MLP train run: the trace-and-cache acceptance
    check (exactly 1 compile, cache hits >= 9, sentinel quiet) attached
    to every bench record so perf drift in the capture layer is caught
    by the same artifact as the dispatch numbers."""
    import numpy as np
    import paddle_tpu.nn as nn
    from paddle_tpu.observability import get_telemetry

    from paddle_tpu.observability.trace import get_tracer

    np.random.seed(0)
    pt.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=model.parameters())
    mse = nn.MSELoss()

    @pt.jit.capture_step
    def step(x, y):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = pt.to_tensor(np.random.randn(4, 8).astype(np.float32))
    y = pt.to_tensor(np.random.randn(4, 1).astype(np.float32))
    first = last = None
    t0 = time.perf_counter()
    for i in range(10):
        loss = float(np.asarray(step(x, y)._data))
        first = loss if first is None else first
        last = loss
    # feed the tracer the measured step time: with the captured
    # program's cost_analysis FLOPs (harvested at compile) and the
    # nominal cpu peak, the record's trace block carries a real
    # analytic-MFU figure even with the TPU unreachable
    get_tracer().on_step((time.perf_counter() - t0) / 10)
    storms = get_telemetry().snapshot()["recompile_storms"]
    return {
        "steps": 10,
        "compiles": step.stats["compiles"],
        "hits": step.stats["hits"],
        "misses": step.stats["misses"],
        "fallback": step.stats["fallback"],
        "sentinel_storms": storms,
        "loss_first": round(first, 6),
        "loss_last": round(last, 6),
        "ok": (step.stats["compiles"] == 1 and step.stats["hits"] >= 9
               and step.stats["fallback"] is None and not storms
               and last < first),
    }


def _amp_contract(pt):
    """AMP O2 acceptance check: the 10-step MLP train run captured with
    bf16-decorated params (fp32 master weights in the optimizer) vs the
    fp32 baseline from identical seeds.  The contract is exactly 1
    compile each, a quiet numerics sentinel riding inside the AMP
    program, a decreasing loss, and a final loss within tolerance of
    fp32 — low precision must change throughput, not where the model
    goes.  Timing uses the same interleaved min-of-rounds discipline as
    ``_numerics_contract`` (on CPU bf16 is emulated, so the ratio is
    reported, not gated)."""
    import numpy as np
    import jax
    import paddle_tpu.nn as nn
    from paddle_tpu.observability.numerics import get_monitor, \
        reset_monitor

    def build(amp):
        reset_monitor()
        if amp:
            get_monitor().enable(cadence=4)
        np.random.seed(3)
        pt.seed(3)
        model = nn.Sequential(nn.Linear(256, 256), nn.ReLU(),
                              nn.Linear(256, 1))
        if amp:
            pt.amp.decorate(model, level="O2", dtype="bfloat16")
        opt = pt.optimizer.Momentum(learning_rate=0.005, momentum=0.9,
                                    parameters=model.parameters(),
                                    multi_precision=True)
        mse = nn.MSELoss()

        @pt.jit.capture_step
        def step(x, y):
            loss = mse(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return step

    rng = np.random.RandomState(4)
    xs = rng.randn(4096, 256).astype(np.float32)
    ys = rng.randn(4096, 1).astype(np.float32)
    y = pt.to_tensor(ys)
    # the AMP step eats bf16 activations end to end — feeding it fp32
    # inputs would silently promote every matmul back to full precision
    x32 = pt.to_tensor(xs)
    x16 = pt.to_tensor(xs).astype("bfloat16")

    def run10(step, x):
        return [float(np.asarray(step(x, y)._data, np.float32))
                for _ in range(10)]

    step_off = build(False)
    losses_off = run10(step_off, x32)
    step_amp = build(True)
    losses_amp = run10(step_amp, x16)
    mon = get_monitor()
    quiet = mon.anomaly_count() == 0
    final_off, final_amp = losses_off[-1], losses_amp[-1]
    gap = abs(final_amp - final_off)
    tol = max(0.05, 0.05 * abs(final_off))

    best = {False: float("inf"), True: float("inf")}
    steps = {False: (step_off, x32), True: (step_amp, x16)}
    for r in range(20):
        order = (False, True) if r % 2 == 0 else (True, False)
        for amp in order:
            s, x = steps[amp]
            jax.block_until_ready(s(x, y)._data)
            t0 = time.perf_counter()
            jax.block_until_ready(s(x, y)._data)
            best[amp] = min(best[amp], time.perf_counter() - t0)
    return {
        "steps": 10,
        "compiles_fp32": step_off.stats["compiles"],
        "compiles_amp": step_amp.stats["compiles"],
        "loss_final_fp32": round(final_off, 6),
        "loss_final_amp": round(final_amp, 6),
        "loss_gap": round(gap, 6),
        "loss_tolerance": round(tol, 6),
        "sentinel_quiet": quiet,
        "step_us_fp32": round(best[False] * 1e6, 1),
        "step_us_amp": round(best[True] * 1e6, 1),
        "amp_speedup_x": round(best[False] / best[True], 3)
        if best[True] else None,
        "ok": (step_off.stats["compiles"] == 1
               and step_amp.stats["compiles"] == 1
               and quiet and gap <= tol
               and losses_off[-1] < losses_off[0]
               and losses_amp[-1] < losses_amp[0]),
    }


def _numerics_contract(pt):
    """Monitored-capture acceptance check: the same 10-step MLP run
    with the numerics sentinel on vs off. The monitor's health outputs
    ride inside the one compiled program, so the contract is exactly
    1 compile each, a bit-identical loss sequence, a quiet sentinel,
    and a per-step overhead ratio under 1.03 (interleaved min-of-rounds
    timing, same noise discipline as ``_bench_all``)."""
    import numpy as np
    import jax
    import paddle_tpu.nn as nn
    from paddle_tpu.observability.numerics import get_monitor, \
        reset_monitor

    def build(monitored):
        reset_monitor()
        if monitored:
            get_monitor().enable(cadence=4)
        np.random.seed(1)
        pt.seed(1)
        model = nn.Sequential(nn.Linear(256, 256), nn.ReLU(),
                              nn.Linear(256, 1))
        opt = pt.optimizer.Momentum(learning_rate=0.005, momentum=0.9,
                                    parameters=model.parameters())
        mse = nn.MSELoss()

        @pt.jit.capture_step
        def step(x, y):
            loss = mse(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return step

    # batch 8192 / ~26ms step: the health program costs a handful of
    # small reductions plus one pass for the grad norm — a near-fixed
    # fee. Against a micro-batch toy step that fee reads as 10%+;
    # the 3% bound is about a realistically-fed step, so the contract
    # measures one.
    rng = np.random.RandomState(2)
    x = pt.to_tensor(rng.randn(8192, 256).astype(np.float32))
    y = pt.to_tensor(rng.randn(8192, 1).astype(np.float32))

    def run10(step):
        return [np.asarray(step(x, y)._data).tobytes()
                for _ in range(10)]

    # correctness leg: train 10 steps each way from identical seeds.
    # the unmonitored step is built while the monitor singleton is
    # disabled, so its traced program carries no health outputs at all.
    step_off = build(False)
    losses_off = run10(step_off)
    step_on = build(True)
    losses_on = run10(step_on)
    mon = get_monitor()
    bitwise = losses_on == losses_off
    quiet = mon.anomaly_count() == 0
    reads = mon.snapshot()["reads"]

    # timing leg: both steps are warm replays now; interleave rounds so
    # load drift hits both columns equally, and run one untimed absorb
    # call before each timed one (same discipline as _bench_all — the
    # runtime defers the previous variant's buffer cleanup into the
    # next dispatch, which would bill off's teardown to on)
    best = {False: float("inf"), True: float("inf")}
    steps = {False: step_off, True: step_on}
    for r in range(20):
        order = (False, True) if r % 2 == 0 else (True, False)
        for monitored in order:
            s = steps[monitored]
            jax.block_until_ready(s(x, y)._data)
            t0 = time.perf_counter()
            jax.block_until_ready(s(x, y)._data)
            best[monitored] = min(best[monitored],
                                  time.perf_counter() - t0)
    best_off, best_on = best[False], best[True]
    ratio = best_on / best_off if best_off else None
    return {
        "steps": 10,
        "compiles_off": step_off.stats["compiles"],
        "compiles_on": step_on.stats["compiles"],
        "monitor_reads": reads,
        "loss_bitwise_identical": bitwise,
        "sentinel_quiet": quiet,
        "step_us_off": round(best_off * 1e6, 1),
        "step_us_on": round(best_on * 1e6, 1),
        "overhead_ratio": round(ratio, 4) if ratio else None,
        "ok": (step_off.stats["compiles"] == 1
               and step_on.stats["compiles"] == 1
               and bitwise and quiet
               and ratio is not None and ratio < 1.03),
    }


def _sdc_contract(pt):
    """SDC-sentry acceptance check: the same 10-step MLP run with the
    replica-fingerprint sentry on vs off. The bitcast word-sum digests
    of every updated parameter and optimizer slot ride inside the one
    compiled program (standalone recording mode — no peer exchange on
    a single process), so the contract is exactly 1 compile each, a
    bit-identical loss sequence (fingerprinting changes no math), the
    cadenced host reads actually booked with zero divergence verdicts,
    and a per-step overhead ratio under 1.01 (interleaved
    min-of-rounds timing, same noise discipline as ``_bench_all``)."""
    import numpy as np
    import jax
    import paddle_tpu.nn as nn
    from paddle_tpu.observability.sdc import get_monitor, reset_monitor

    def build(monitored):
        reset_monitor()
        if monitored:
            get_monitor().enable(cadence=4, halt=False)
        np.random.seed(5)
        pt.seed(5)
        model = nn.Sequential(nn.Linear(256, 256), nn.ReLU(),
                              nn.Linear(256, 1))
        opt = pt.optimizer.Momentum(learning_rate=0.005, momentum=0.9,
                                    parameters=model.parameters())
        mse = nn.MSELoss()

        @pt.jit.capture_step
        def step(x, y):
            loss = mse(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return step

    # batch 8192 / ~26ms step: the digest program is one bitcast + sum
    # per leaf — a near-fixed fee; the 1% bound is about a
    # realistically-fed step, so the contract measures one (same
    # sizing rationale as _numerics_contract)
    rng = np.random.RandomState(6)
    x = pt.to_tensor(rng.randn(8192, 256).astype(np.float32))
    y = pt.to_tensor(rng.randn(8192, 1).astype(np.float32))

    def run10(step):
        return [np.asarray(step(x, y)._data).tobytes()
                for _ in range(10)]

    # correctness leg: train 10 steps each way from identical seeds.
    # the unfingerprinted step is built while the singleton is
    # disabled, so its traced program carries no digest outputs at all.
    step_off = build(False)
    losses_off = run10(step_off)
    step_on = build(True)
    losses_on = run10(step_on)
    mon = get_monitor().flush()
    snap = mon.snapshot()
    bitwise = losses_on == losses_off
    clean = snap["divergences_total"] == 0

    # timing leg: both steps are warm replays now; interleave rounds so
    # load drift hits both columns equally (absorb-call discipline as
    # in _bench_all / _numerics_contract)
    best = {False: float("inf"), True: float("inf")}
    steps = {False: step_off, True: step_on}
    for r in range(20):
        order = (False, True) if r % 2 == 0 else (True, False)
        for monitored in order:
            s = steps[monitored]
            jax.block_until_ready(s(x, y)._data)
            t0 = time.perf_counter()
            jax.block_until_ready(s(x, y)._data)
            best[monitored] = min(best[monitored],
                                  time.perf_counter() - t0)
    best_off, best_on = best[False], best[True]
    ratio = best_on / best_off if best_off else None
    return {
        "steps": 10,
        "compiles_off": step_off.stats["compiles"],
        "compiles_on": step_on.stats["compiles"],
        "fingerprint_reads": snap["reads"],
        "last_fingerprint": snap["last_fingerprint"],
        "divergences_total": snap["divergences_total"],
        "loss_bitwise_identical": bitwise,
        "step_us_off": round(best_off * 1e6, 1),
        "step_us_on": round(best_on * 1e6, 1),
        "overhead_ratio": round(ratio, 4) if ratio else None,
        "ok": (step_off.stats["compiles"] == 1
               and step_on.stats["compiles"] == 1
               and bitwise and clean
               and snap["reads"] >= 2
               and ratio is not None and ratio < 1.01),
    }


def _memory_contract(pt):
    """Memory-observability acceptance check: the same captured MLP
    run with the memory monitor on vs off. The footprint harvest rides
    the compile (AOT memory_analysis on the cache-shared program) and
    the watermark sampling is a host-side allocator read per step, so
    the contract is exactly 1 compile each, a bit-identical loss
    sequence (monitoring changes no math), the per-program footprint
    actually booked, and a per-step overhead ratio under 1.01 with
    sampling on every step (interleaved min-of-rounds timing, same
    noise discipline as ``_bench_all``)."""
    import numpy as np
    import jax
    import paddle_tpu.nn as nn
    from paddle_tpu.observability.memory import get_memory_monitor, \
        reset_memory_monitor

    def build(monitored):
        reset_memory_monitor()
        if monitored:
            get_memory_monitor().enable(sample_every=1)
        np.random.seed(3)
        pt.seed(3)
        model = nn.Sequential(nn.Linear(256, 256), nn.ReLU(),
                              nn.Linear(256, 1))
        opt = pt.optimizer.Momentum(learning_rate=0.005, momentum=0.9,
                                    parameters=model.parameters())
        mse = nn.MSELoss()

        @pt.jit.capture_step
        def step(x, y):
            loss = mse(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return step

    # batch 8192 / ~26ms step: the allocator read + census identity map
    # is a near-fixed per-step fee; the 1% bound is about a
    # realistically-fed step, so the contract measures one (same
    # sizing rationale as _numerics_contract)
    rng = np.random.RandomState(4)
    x = pt.to_tensor(rng.randn(8192, 256).astype(np.float32))
    y = pt.to_tensor(rng.randn(8192, 1).astype(np.float32))

    def run10(step):
        return [np.asarray(step(x, y)._data).tobytes()
                for _ in range(10)]

    # correctness leg: train 10 steps each way from identical seeds.
    # the unmonitored step is built while the singleton is disabled, so
    # its capture registers no provider and harvests nothing.
    step_off = build(False)
    losses_off = run10(step_off)
    step_on = build(True)
    losses_on = run10(step_on)
    mm = get_memory_monitor()
    snap = mm.snapshot()
    harvested = bool(snap["programs"])
    census = mm.live_buffer_census()
    bitwise = losses_on == losses_off

    # timing leg: both steps are warm replays now; interleave rounds so
    # load drift hits both columns equally (absorb-call discipline as
    # in _bench_all / _numerics_contract)
    best = {False: float("inf"), True: float("inf")}
    steps = {False: step_off, True: step_on}
    for r in range(20):
        order = (False, True) if r % 2 == 0 else (True, False)
        for monitored in order:
            s = steps[monitored]
            jax.block_until_ready(s(x, y)._data)
            t0 = time.perf_counter()
            jax.block_until_ready(s(x, y)._data)
            best[monitored] = min(best[monitored],
                                  time.perf_counter() - t0)
    best_off, best_on = best[False], best[True]
    ratio = best_on / best_off if best_off else None
    return {
        "steps": 10,
        "compiles_off": step_off.stats["compiles"],
        "compiles_on": step_on.stats["compiles"],
        "footprint_harvested": harvested,
        "fit_ok": snap["fit_ok"],
        "census_param_bytes": census["by_category"].get("param", 0),
        "oom_events": snap["oom_events"],
        "loss_bitwise_identical": bitwise,
        "step_us_off": round(best_off * 1e6, 1),
        "step_us_on": round(best_on * 1e6, 1),
        "overhead_ratio": round(ratio, 4) if ratio else None,
        "ok": (step_off.stats["compiles"] == 1
               and step_on.stats["compiles"] == 1
               and harvested and bitwise
               and census["by_category"].get("param", 0) > 0
               and snap["oom_events"] == 0
               and ratio is not None and ratio < 1.01),
    }


def _fusion_bench(pt):
    """Fused-vs-unfused captured-step CPU timing plus the pass's own
    stats. The same transformer block (LN→matmul, matmul+bias+gelu,
    residual+LN) is captured twice — once with ``PT_FUSION_PASS=0``,
    once rewritten. On CPU every rewritten cluster dispatches to the
    inline XLA mirror (``tpu_unreachable`` fast-fail), so the fused
    column measures the pass itself, never Pallas interpret overhead;
    the acceptance bar is fused no slower than unfused."""
    import numpy as np
    import jax
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.ops import fusion_pass as fp

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.ln1 = nn.LayerNorm(64)
            self.fc1 = nn.Linear(64, 128)
            self.fc2 = nn.Linear(128, 64)
            self.ln2 = nn.LayerNorm(64)

        def forward(self, x):
            h = self.fc2(F.gelu(self.fc1(self.ln1(x))))
            return self.ln2(x, residual=h)

    x = pt.to_tensor(
        np.random.RandomState(0).randn(32, 64).astype(np.float32))

    def timed(enabled):
        os.environ["PT_FUSION_PASS"] = "1" if enabled else "0"
        fp.reset_stats()
        np.random.seed(0)
        pt.seed(0)
        model = Block()

        @pt.jit.capture_step
        def step(inp):
            return model(inp)

        out = step(x)  # compile (fusion pass runs inside this trace)
        stats = fp.summary()
        best = float("inf")
        for _ in range(50):
            t0 = time.perf_counter()
            jax.block_until_ready(step(x)._data)
            best = min(best, time.perf_counter() - t0)
        return best, stats, np.asarray(out._data)

    prev = os.environ.get("PT_FUSION_PASS")
    try:
        t_unfused, _, out_u = timed(False)
        t_fused, stats, out_f = timed(True)
    finally:
        if prev is None:
            os.environ.pop("PT_FUSION_PASS", None)
        else:
            os.environ["PT_FUSION_PASS"] = prev
    import numpy as np2
    diff = float(np2.max(np2.abs(out_u - out_f)))
    return {
        "captured_step_unfused_us": round(t_unfused * 1e6, 1),
        "captured_step_fused_us": round(t_fused * 1e6, 1),
        "fused_vs_unfused_ratio": round(t_fused / t_unfused, 3)
        if t_unfused else None,
        "rewrites": stats["rewrites"],
        "fallbacks": stats["fallbacks"],
        "max_abs_diff": diff,
        "ok": bool(stats["rewrites"]) and diff <= 1e-5,
    }


def main():
    import jax
    import jax.numpy as jnp
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt

    x = jnp.ones(SHAPE, jnp.float32)
    y = jnp.full(SHAPE, 0.5, jnp.float32)

    def raw_jax():
        z = x
        for _ in range(N_OPS):
            z = z * y + y
        return z

    tx = pt.to_tensor(x)
    ty = pt.to_tensor(y)
    tx.stop_gradient = True
    ty.stop_gradient = True

    def tape_off():
        z = tx
        for _ in range(N_OPS):
            z = z * ty + ty
        return z

    gx = pt.to_tensor(x)
    gy = pt.to_tensor(y)
    gx.stop_gradient = False
    gy.stop_gradient = False

    def tape_on():
        z = gx
        for _ in range(N_OPS):
            z = z * gy + gy
        return z

    from paddle_tpu.observability import get_telemetry
    from paddle_tpu.observability.trace import get_tracer
    tel = get_telemetry().enable()
    # tracing on for the whole bench: capture harvests per-program
    # cost_analysis FLOPs at compile time, replays record compute spans
    tr = get_tracer().enable()
    # goodput ledger decomposes that same span ring; its block rides on
    # the record like telemetry/trace do
    from paddle_tpu.observability.goodput import get_goodput
    gp = get_goodput().enable()
    # graph audit on for the whole bench: every capture_step compile in
    # this file (the chain, the contract runs, the fusion A/B) gets its
    # pre-fusion jaxpr audited at capture time — replays cost nothing
    from paddle_tpu.tools.audit import runtime as audit_rt
    audit_rt.enable()

    # the chain takes its inputs as ARGUMENTS: closed-over operands let
    # XLA constant-fold the whole program into one literal, which would
    # report dispatch-of-a-constant (~0.03us/op) instead of a runnable
    # step and wreck the captured/jit ratio below
    def chain(a, b):
        z = a
        for _ in range(N_OPS):
            z = z * b + b
        return z

    jitted = jax.jit(chain)
    jitted(x, y)  # compile outside the timing

    cx = pt.to_tensor(x)
    cy = pt.to_tensor(y)
    cx.stop_gradient = True
    cy.stop_gradient = True

    @pt.jit.capture_step
    def cap_chain(a, b):
        z = a
        for _ in range(N_OPS):
            z = z * b + b
        return z

    block_jax = lambda z: jax.block_until_ready(z)
    block_pt = lambda z: jax.block_until_ready(z._data)

    us = _bench_all([
        ("raw_jax", raw_jax, block_jax),
        ("tape_off", tape_off, block_pt),
        ("tape_on", tape_on, block_pt),
        ("captured_step", lambda: cap_chain(cx, cy), block_pt),
        ("jit_chain", lambda: jitted(x, y), block_jax),
    ])
    res = {
        "metric": "eager_dispatch_overhead",
        "unit": "us/op",
        **{k: round(v * 1e6, 2) for k, v in us.items()},
        "n_ops": N_OPS,
        "shape": list(SHAPE),
    }
    # each op here is mul+add fused in one funnel call; normalize names
    res["tape_overhead_ratio"] = round(res["tape_on"] / res["raw_jax"], 2) \
        if res["raw_jax"] else None
    res["captured_vs_jit_ratio"] = \
        round(res["captured_step"] / res["jit_chain"], 2) \
        if res["jit_chain"] else None
    res["value"] = res["tape_on"]
    res["precision"] = "fp32"
    res["capture"] = _capture_contract(pt)
    res["fusion"] = _fusion_bench(pt)
    res["numerics_contract"] = _numerics_contract(pt)
    res["amp_contract"] = _amp_contract(pt)
    res["sdc_contract"] = _sdc_contract(pt)
    res["memory_contract"] = _memory_contract(pt)
    res["telemetry"] = tel.snapshot()
    res["trace"] = tr.snapshot()
    res["goodput"] = gp.snapshot()
    from paddle_tpu.observability.numerics import get_monitor
    res["numerics"] = get_monitor().snapshot()
    from paddle_tpu.observability.sdc import get_monitor as _sdc_mon
    res["sdc"] = _sdc_mon().snapshot()
    from paddle_tpu.observability.memory import get_memory_monitor
    res["memory"] = get_memory_monitor().snapshot()
    res["audit"] = audit_rt.snapshot()
    from paddle_tpu.distributed.supervisor import supervision_snapshot
    res["supervision"] = supervision_snapshot()
    try:
        from paddle_tpu.observability import cluster_snapshot
        res["telemetry_cluster"] = cluster_snapshot(
            url=os.environ.get("PT_AGGREGATOR_URL") or None)
    except Exception as e:  # snapshot is best-effort by contract
        res["telemetry_cluster"] = {"error": str(e)[:200]}
    print(json.dumps(res))


if __name__ == "__main__":
    main()
