"""``paddle.geometric`` — graph-learning ops (ref:
``python/paddle/geometric/__init__.py``).

TPU stance: the reference backs these with hand-written CUDA scatter/gather
kernels (``paddle/phi/kernels/gpu/graph_send_recv_kernel.cu``); here the
reduction ops lower to XLA's native ``scatter-add/min/max`` HLO via
``jax.ops.segment_*`` — one fused program, differentiable through the tape.
The sampling / reindex ops are data-dependent-shape by nature and run on the
host (they are CPU/GPU sync points in the reference too).
"""
from .math import segment_sum, segment_mean, segment_min, segment_max  # noqa: F401
from .message_passing import send_u_recv, send_ue_recv, send_uv  # noqa: F401
from .reindex import reindex_graph, reindex_heter_graph  # noqa: F401
from .sampling import sample_neighbors, weighted_sample_neighbors  # noqa: F401

__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv",
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "reindex_graph", "reindex_heter_graph",
    "sample_neighbors", "weighted_sample_neighbors",
]
