"""Neighbor sampling over CSC graphs (ref:
``python/paddle/geometric/sampling/neighbors.py``).

Data-dependent output size -> host op (the reference's GPU kernel also
round-trips counts through the host to size its outputs). Randomness draws
from the framework generator's seed so ``paddle_tpu.seed`` reproduces runs.
Weighted sampling-without-replacement uses exponential-race keys
(Efraimidis-Spirakis): draw ``e_i ~ Exp(w_i)`` per edge and keep the
``sample_size`` smallest — one vectorised pass, no per-node rejection loop.
"""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor
from ..framework import random as _random

__all__ = ["sample_neighbors", "weighted_sample_neighbors"]


def _rng():
    """Fresh numpy RNG per call, advancing the framework generator's
    counter so successive sampling calls draw different neighborhoods
    while ``paddle_tpu.seed`` still reproduces the whole sequence."""
    import jax
    key = _random.default_generator.next_key()
    return np.random.default_rng(
        np.asarray(jax.random.key_data(key), dtype=np.uint32))


def _sample(row, colptr, input_nodes, sample_size, eids, return_eids,
            weights=None):
    row = np.asarray(row).reshape(-1)
    colptr = np.asarray(colptr).reshape(-1)
    nodes = np.asarray(input_nodes).reshape(-1)
    eid_arr = np.asarray(eids).reshape(-1) if eids is not None else None
    w = np.asarray(weights).reshape(-1) if weights is not None else None
    rng = _rng()

    out_n, out_c, out_e = [], [], []
    for n in nodes.tolist():
        beg, end = int(colptr[n]), int(colptr[n + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(beg, end)
        elif w is not None:
            keys = rng.exponential(size=deg) / np.maximum(w[beg:end], 1e-30)
            sel = beg + np.argpartition(keys, sample_size)[:sample_size]
        else:
            sel = beg + rng.choice(deg, size=sample_size, replace=False)
        out_n.append(row[sel])
        out_c.append(len(sel))
        if return_eids:
            if eid_arr is None:
                raise ValueError("return_eids=True requires eids")
            out_e.append(eid_arr[sel])

    neighbors = (np.concatenate(out_n) if out_n
                 else np.empty((0,), row.dtype))
    counts = np.asarray(out_c, dtype=np.int32)
    if return_eids:
        e = (np.concatenate(out_e) if out_e
             else np.empty((0,), eid_arr.dtype))
        return Tensor(neighbors), Tensor(counts), Tensor(e)
    return Tensor(neighbors), Tensor(counts)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    return _sample(row, colptr, input_nodes, int(sample_size), eids,
                   return_eids)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    return _sample(row, colptr, input_nodes, int(sample_size), eids,
                   return_eids, weights=edge_weight)
