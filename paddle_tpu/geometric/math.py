"""Segment reductions (ref: ``python/paddle/geometric/math.py``).

Reference semantics: ``segment_ids`` is sorted non-negative int32/int64;
output has ``max(segment_ids)+1`` rows; segments that never appear produce
rows of 0 (the CUDA kernel leaves the zero-initialised output untouched —
``paddle/phi/kernels/cpu/segment_pool_kernel.cc``). XLA needs a static row
count, so the row count is read eagerly from ``segment_ids`` at op-build
time (these APIs are eager/host-driven in the reference too).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.op_utils import ensure_tensor, nary

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max"]


def _num_segments(segment_ids) -> int:
    ids = np.asarray(segment_ids)
    if ids.size == 0:
        return 0
    return int(ids.max()) + 1


def _segment_reduce(data, segment_ids, pool_type, name):
    data = ensure_tensor(data)
    segment_ids = ensure_tensor(segment_ids)
    n = _num_segments(segment_ids)

    def f(d, ids):
        return _apply_segment(d, ids, n, pool_type)

    return nary(f, [data, segment_ids], name=name)


def _apply_segment(d, ids, n, pool_type):
    """Pure segment reduce; also reused by message_passing."""
    if pool_type == "sum":
        return jax.ops.segment_sum(d, ids, num_segments=n)
    if pool_type == "mean":
        total = jax.ops.segment_sum(d, ids, num_segments=n)
        count = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), ids,
                                    num_segments=n)
        count = jnp.maximum(count, 1).reshape((n,) + (1,) * (d.ndim - 1))
        return total / count
    if pool_type in ("min", "max"):
        fn = jax.ops.segment_min if pool_type == "min" else jax.ops.segment_max
        out = fn(d, ids, num_segments=n)
        # empty segments: the identity element (±inf / dtype extremum)
        # must become 0 to match the reference's zero-init kernels
        count = jax.ops.segment_sum(jnp.ones((d.shape[0],), jnp.int32), ids,
                                    num_segments=n)
        mask = (count > 0).reshape((n,) + (1,) * (d.ndim - 1))
        return jnp.where(mask, out, jnp.zeros_like(out))
    raise ValueError(f"unknown segment pool type {pool_type!r}")


def segment_sum(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids, "sum", "segment_sum")


def segment_mean(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids, "mean", "segment_mean")


def segment_min(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids, "min", "segment_min")


def segment_max(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids, "max", "segment_max")
