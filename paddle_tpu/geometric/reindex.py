"""Graph reindexing (ref: ``python/paddle/geometric/reindex.py``).

Output shape depends on how many distinct node ids appear, so these run on
the host (the reference's kernel is likewise a hash-table build —
``paddle/phi/kernels/gpu/graph_reindex_kernel.cu`` — and syncs the stream).
``value_buffer``/``index_buffer`` are accepted for API parity; the hash-table
they pre-allocate in the reference has no analog here.
"""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor

__all__ = ["reindex_graph", "reindex_heter_graph"]


def _reindex(x, neighbor_arrays):
    """Shared core: build the old-id -> new-id map with x first, then
    unseen neighbor ids in order of first appearance."""
    x = np.asarray(x)
    mapping = {int(v): i for i, v in enumerate(x)}
    out_nodes = list(x.tolist())
    reindexed = []
    for neigh in neighbor_arrays:
        idx = np.empty(len(neigh), dtype=x.dtype)
        for j, v in enumerate(np.asarray(neigh).tolist()):
            pos = mapping.get(int(v))
            if pos is None:
                pos = len(out_nodes)
                mapping[int(v)] = pos
                out_nodes.append(int(v))
            idx[j] = pos
        reindexed.append(idx)
    return reindexed, np.asarray(out_nodes, dtype=x.dtype)


def _dst_of(x_len, count, dtype):
    return np.repeat(np.arange(x_len, dtype=dtype), np.asarray(count))


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    xs = np.asarray(x)
    (reindex_src,), out_nodes = _reindex(xs, [np.asarray(neighbors)])
    reindex_dst = _dst_of(len(xs), count, xs.dtype)
    return (Tensor(reindex_src), Tensor(reindex_dst), Tensor(out_nodes))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    xs = np.asarray(x)
    neighs = [np.asarray(n) for n in neighbors]
    srcs, out_nodes = _reindex(xs, neighs)
    dsts = [_dst_of(len(xs), c, xs.dtype) for c in count]
    return (Tensor(np.concatenate(srcs)), Tensor(np.concatenate(dsts)),
            Tensor(out_nodes))
