"""Graph message passing (ref:
``python/paddle/geometric/message_passing/send_recv.py``).

``send_u_recv`` gathers node features along ``src_index`` and
scatter-reduces them at ``dst_index`` — on TPU the gather+reduce pair fuses
into a single XLA scatter program instead of materialising the per-edge
message tensor (the same memory-saving the reference's fused
``graph_send_recv`` CUDA kernel exists for).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..ops.op_utils import ensure_tensor, nary
from .math import _apply_segment

__all__ = ["send_u_recv", "send_ue_recv", "send_uv"]

_REDUCE = ("sum", "mean", "max", "min")
_MESSAGE = ("add", "sub", "mul", "div")


def _out_rows(dst_index, out_size):
    if out_size is not None:
        n = int(out_size.item()) if hasattr(out_size, "item") else int(out_size)
        if n > 0:
            return n
    ids = np.asarray(dst_index)
    return int(ids.max()) + 1 if ids.size else 0


def _broadcast_edge(a, b):
    """Right-align feature dims the way the reference broadcasts x vs e."""
    nd = max(a.ndim, b.ndim)
    a = a.reshape((a.shape[0],) + (1,) * (nd - a.ndim) + a.shape[1:])
    b = b.reshape((b.shape[0],) + (1,) * (nd - b.ndim) + b.shape[1:])
    return a, b


def _message(x_e, y_e, message_op):
    x_e, y_e = _broadcast_edge(x_e, y_e)
    if message_op == "add":
        return x_e + y_e
    if message_op == "sub":
        return x_e - y_e
    if message_op == "mul":
        return x_e * y_e
    if message_op == "div":
        return x_e / y_e
    raise ValueError(
        f"message_op should be one of {_MESSAGE}, got {message_op!r}")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    if reduce_op not in _REDUCE:
        raise ValueError(
            f"reduce_op should be one of {_REDUCE}, got {reduce_op!r}")
    x = ensure_tensor(x)
    src_index = ensure_tensor(src_index)
    dst_index = ensure_tensor(dst_index)
    n = _out_rows(dst_index, out_size)

    def f(d, src, dst):
        return _apply_segment(d[src], dst, n, reduce_op)

    return nary(f, [x, src_index, dst_index], name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    if reduce_op not in _REDUCE:
        raise ValueError(
            f"reduce_op should be one of {_REDUCE}, got {reduce_op!r}")
    x, y = ensure_tensor(x), ensure_tensor(y)
    src_index = ensure_tensor(src_index)
    dst_index = ensure_tensor(dst_index)
    n = _out_rows(dst_index, out_size)

    def f(xd, yd, src, dst):
        msg = _message(xd[src], yd, message_op)
        return _apply_segment(msg, dst, n, reduce_op)

    return nary(f, [x, y, src_index, dst_index], name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    src_index = ensure_tensor(src_index)
    dst_index = ensure_tensor(dst_index)

    def f(xd, yd, src, dst):
        return _message(xd[src], yd[dst], message_op)

    return nary(f, [x, y, src_index, dst_index], name="send_uv")
