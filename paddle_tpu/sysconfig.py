"""``paddle.sysconfig`` (ref: ``python/paddle/sysconfig.py``)."""
import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory containing the framework's C++ headers (the native host
    core's ``common.h`` — the TPU compute path needs no C++ headers)."""
    return os.path.join(os.path.dirname(__file__), "core", "native")


def get_lib():
    """Directory containing the compiled native core library (built on
    demand; see ``core/build.py``)."""
    from .core.build import build_ptcore, _cache_dir
    build_ptcore()
    return _cache_dir()
