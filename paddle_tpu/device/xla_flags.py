"""XLA latency-hiding-scheduler / async-collective enablement.

The compute↔communication overlap built into the train step (bucketed
gradient reductions placed mid-backward, double-buffered pipeline hops —
``distributed/grad_buckets.py``, ``fleet/meta_parallel/pp_spmd.py``)
only pays off if XLA's scheduler is allowed to run collectives
asynchronously under compute. On TPU that is the latency-hiding
scheduler plus the async-collective/collective-fusion passes; they are
process-level compiler flags, not per-program options, so they must be
in ``XLA_FLAGS``/``LIBTPU_INIT_ARGS`` before the backend initializes.

``enable_overlap_flags()`` is called by the hybrid entry points (fleet
init, the MULTICHIP dryrun, bench) and is safe to call any time: it is
idempotent, never overrides a flag the operator already set, and warns
instead of lying when the backend is already up.

The flag set is TPU-generation debug options: XLA builds that do not
register them (the CPU wheel) ABORT the process at backend init when
they appear in ``XLA_FLAGS`` (``parse_flags_from_env.cc`` is fatal on
unknown names). The helper therefore always stages the flags in
``LIBTPU_INIT_ARGS`` (read by libtpu alone — inert elsewhere) but
touches ``XLA_FLAGS`` only when the process explicitly targets a TPU
backend (``JAX_PLATFORMS``/``JAX_PLATFORM_NAME`` name tpu) or the
operator forces it.

Env controls:
 - ``PT_XLA_OVERLAP_FLAGS=0`` — disable entirely (the helper becomes a
   no-op returning []).
 - ``PT_XLA_OVERLAP_FLAGS=force`` — apply even without a detectable TPU
   runtime (operator asserts their XLA build knows the flags).
 - ``PT_XLA_OVERLAP_EXTRA`` — extra space-separated flags appended after
   the defaults (operator escape hatch for per-generation tuning).
"""
from __future__ import annotations

import os
import warnings

__all__ = ["OVERLAP_XLA_FLAGS", "OVERLAP_LIBTPU_FLAGS",
           "enable_overlap_flags", "overlap_flags_active"]

# Scheduler + async-collective set. The latency-hiding scheduler
# reorders independent collectives under compute; the async flags make
# each collective op non-blocking (start/done pair) so there is
# something to reorder. Names follow the xla repo's debug_options.
OVERLAP_XLA_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)
# libtpu reads the same debug options through LIBTPU_INIT_ARGS on real
# TPU runtimes; keep both surfaces in sync.
OVERLAP_LIBTPU_FLAGS = OVERLAP_XLA_FLAGS

_applied = False


def _flag_name(flag):
    return flag.split("=", 1)[0]


def _merge(env_value, flags):
    """Append flags whose NAME is not already present (operator wins)."""
    present = {_flag_name(f) for f in env_value.split() if f}
    added = [f for f in flags if _flag_name(f) not in present]
    merged = (env_value + " " + " ".join(added)).strip() if added \
        else env_value
    return merged, added


def _backend_initialized():
    import jax
    try:
        # the public probe: backends() materializes the client, so ask
        # the lower-level registry instead
        from jax._src import xla_bridge
        return xla_bridge.backends_are_initialized()
    except Exception:
        try:
            import jax._src.xla_bridge as xb
            return bool(getattr(xb, "_backends", None))
        except Exception:
            return False


def _tpu_runtime_plausible():
    """True when this process explicitly targets a TPU backend (the jax
    platform envs name one). libtpu merely being importable is NOT
    enough: on a TPU-less host jax falls back to the in-process CPU
    client, whose flag table is the one that parses ``XLA_FLAGS``.
    Must not touch jax (runs before backend init)."""
    plat = (os.environ.get("JAX_PLATFORMS", "")
            + " " + os.environ.get("JAX_PLATFORM_NAME", "")).lower()
    return "tpu" in plat


def overlap_flags_active():
    """True when every overlap flag name is present in ``XLA_FLAGS`` or
    ``LIBTPU_INIT_ARGS`` (on real TPU runtimes the libtpu surface is
    the effective carrier)."""
    present = {_flag_name(f)
               for env in ("XLA_FLAGS", "LIBTPU_INIT_ARGS")
               for f in os.environ.get(env, "").split() if f}
    return all(_flag_name(f) in present for f in OVERLAP_XLA_FLAGS)


def enable_overlap_flags(extra=(), warn_if_late=True):
    """Install the overlap flag set into the process env (idempotent).

    Returns the list of flags newly added to ``XLA_FLAGS`` (empty when
    disabled via ``PT_XLA_OVERLAP_FLAGS=0``, no TPU runtime is present
    and the set wasn't forced, already applied, or every name was
    operator-set). Flags the operator already pinned — in ``XLA_FLAGS``
    or via ``PT_XLA_OVERLAP_EXTRA`` — are never overridden, only absent
    names are appended.
    """
    global _applied
    mode = os.environ.get("PT_XLA_OVERLAP_FLAGS", "auto")
    if mode in ("0", "false", "off"):
        return []
    extra_env = tuple(os.environ.get("PT_XLA_OVERLAP_EXTRA", "").split())
    flags = tuple(OVERLAP_XLA_FLAGS) + tuple(extra) + extra_env
    # LIBTPU_INIT_ARGS is read by libtpu alone — safe to stage on any
    # host, and the effective flag carrier on real TPU runtimes
    lib_merged, lib_added = _merge(
        os.environ.get("LIBTPU_INIT_ARGS", ""), flags)
    if lib_added:
        os.environ["LIBTPU_INIT_ARGS"] = lib_merged
    if mode not in ("1", "force", "always") and not _tpu_runtime_plausible():
        # a CPU/GPU XLA build hard-aborts at backend init on the
        # unknown TPU flag names — stay out of XLA_FLAGS unless the
        # process explicitly targets tpu (or the operator forces it)
        return []
    merged, added = _merge(os.environ.get("XLA_FLAGS", ""), flags)
    if not added:
        _applied = True
        return []
    if _backend_initialized() and warn_if_late:
        warnings.warn(
            "enable_overlap_flags() called after the XLA backend "
            "initialized; the latency-hiding-scheduler flags will only "
            "take effect in processes that set them before first device "
            "use (export XLA_FLAGS or call this at import time)",
            RuntimeWarning, stacklevel=2)
    os.environ["XLA_FLAGS"] = merged
    _applied = True
    return added
