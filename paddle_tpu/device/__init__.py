"""``paddle.device`` namespace (ref: ``python/paddle/device/``)."""
from ..framework.device import (  # noqa: F401
    set_device, get_device, get_all_devices, device_count,
    is_compiled_with_cuda, is_compiled_with_rocm, is_compiled_with_xpu,
    is_compiled_with_tpu, is_compiled_with_cinn,
    is_compiled_with_custom_device, device_guard, Place, CPUPlace, TPUPlace,
    CUDAPlace, CustomPlace, XPUPlace,
)
from .plugin import (  # noqa: F401
    load_custom_runtime_lib, load_custom_device_plugins, registered_plugins)
from .xla_flags import (  # noqa: F401
    enable_overlap_flags, overlap_flags_active, OVERLAP_XLA_FLAGS)

__all__ = ["set_device", "get_device", "get_all_devices", "device_count",
           "is_compiled_with_cuda", "is_compiled_with_tpu", "cuda",
           "get_available_device", "get_available_custom_device",
           "load_custom_runtime_lib", "load_custom_device_plugins",
           "get_cudnn_version", "IPUPlace", "is_compiled_with_ipu",
           "get_all_device_type", "get_all_custom_device_type",
           "Stream", "Event", "current_stream", "set_stream",
           "stream_guard", "synchronize",
           "enable_overlap_flags", "overlap_flags_active"]


def get_available_device():
    return get_all_devices()


def get_available_custom_device():
    return [d for d in get_all_devices() if d.startswith("tpu")]


class cuda:
    """Parity shim for paddle.device.cuda — maps to the TPU accelerator."""

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        import jax
        # block until all dispatched work completes
        (jax.device_put(0) + 0).block_until_ready()

    @staticmethod
    def empty_cache():
        import gc
        gc.collect()

    @staticmethod
    def max_memory_allocated(device=None):
        return _mem_stat("peak_bytes_in_use")

    @staticmethod
    def memory_allocated(device=None):
        return _mem_stat("bytes_in_use")


# -- stream/event surface (ref device/__init__.py:410-877) ---------------
# XLA owns scheduling on TPU: one ordered stream per device, host-side
# synchronization is a block_until_ready. These objects keep the API so
# CUDA-era scripts run; "waiting" degrades to full-device sync.

def get_cudnn_version():
    """ref ``device/__init__.py``: None when not built with cuDNN."""
    return None


def is_compiled_with_ipu():
    return False


class IPUPlace:
    def __init__(self):
        raise RuntimeError("paddle_tpu is not compiled with IPU support")


def get_all_device_type():
    """ref: device types this build can drive (the jax platform name —
    a gpu backend must not masquerade as tpu)."""
    import jax
    kinds = {"cpu"}
    try:
        for d in jax.devices():
            kinds.add(d.platform)
    except Exception:
        pass
    return sorted(kinds)


def get_all_custom_device_type():
    return [t for t in get_all_device_type() if t not in ("cpu", "gpu")]


class Event:
    """ref ``device/__init__.py:410``. Records a point in the device
    timeline; on XLA the only observable point is "everything submitted
    so far is done", via synchronize."""

    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self._recorded = False

    def record(self, stream=None):
        self._recorded = True

    def query(self):
        return True  # XLA execution is ordered; nothing is "pending"

    def synchronize(self):
        synchronize()


class Stream:
    """ref ``device/__init__.py:555``. XLA has one compute stream per
    chip; this object exists so stream-annotated code runs unchanged."""

    def __init__(self, device=None, priority=2, blocking=False):
        self.device = device

    def record_event(self, event=None):
        ev = event or Event()
        ev.record(self)
        return ev

    def wait_event(self, event):
        synchronize()

    def wait_stream(self, stream):
        synchronize()

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


def set_stream(stream):
    global _current_stream
    prev = _current_stream
    _current_stream = stream
    return prev


class stream_guard:
    def __init__(self, stream):
        self._stream = stream

    def __enter__(self):
        self._prev = set_stream(self._stream)
        return self._stream

    def __exit__(self, *exc):
        set_stream(self._prev)
        return False


def synchronize(device=None):
    """Block until every submitted computation finished (ref
    ``device/__init__.py:877``). XLA dispatch is async and ORDERED per
    device, so joining on a fresh trailing computation joins everything
    submitted before it (same pattern as ``cuda.synchronize``);
    effects_barrier additionally joins effectful ones."""
    import jax
    try:
        jax.effects_barrier()
    except Exception:
        pass
    (jax.device_put(0) + 0).block_until_ready()


# -- cuda-namespace parity additions (alias the device-level surface;
# ref device/cuda/__init__.py) -------------------------------------------
cuda.Stream = Stream
cuda.Event = Event
cuda.current_stream = staticmethod(current_stream)
cuda.stream_guard = stream_guard


def _mem_stat(which, device=None):
    # the ONE allocator read every memory shim routes through: guarded
    # (never initializes a jax backend just to ask), 0 when absent
    from ..observability.memory import device_memory_stat
    return device_memory_stat(which)


def _memory_reserved(device=None):
    return _mem_stat("bytes_reserved") or _mem_stat("bytes_in_use")


def _max_memory_reserved(device=None):
    return _mem_stat("peak_bytes_in_use")


cuda.memory_reserved = staticmethod(_memory_reserved)
cuda.max_memory_reserved = staticmethod(_max_memory_reserved)


def _get_device_properties(device=None):
    import jax
    d = jax.local_devices()[0]
    class _Props:
        name = getattr(d, "device_kind", "cpu")
        major, minor = 0, 0
        total_memory = _mem_stat("bytes_limit")
        multi_processor_count = 1
    return _Props()


cuda.get_device_properties = staticmethod(_get_device_properties)
cuda.get_device_name = staticmethod(
    lambda device=None: _get_device_properties(device).name)
cuda.get_device_capability = staticmethod(lambda device=None: (0, 0))


class xpu:
    """``paddle.device.xpu`` parity shim (no XPU in a TPU build; the
    one exported name joins the ordered XLA stream like the others)."""

    @staticmethod
    def synchronize(device=None):
        return synchronize(device)
