"""``paddle.device`` namespace (ref: ``python/paddle/device/``)."""
from ..framework.device import (  # noqa: F401
    set_device, get_device, get_all_devices, device_count,
    is_compiled_with_cuda, is_compiled_with_rocm, is_compiled_with_xpu,
    is_compiled_with_tpu, is_compiled_with_cinn,
    is_compiled_with_custom_device, device_guard, Place, CPUPlace, TPUPlace,
    CUDAPlace, CustomPlace, XPUPlace,
)
from .plugin import (  # noqa: F401
    load_custom_runtime_lib, load_custom_device_plugins, registered_plugins)

__all__ = ["set_device", "get_device", "get_all_devices", "device_count",
           "is_compiled_with_cuda", "is_compiled_with_tpu", "cuda",
           "get_available_device", "get_available_custom_device",
           "load_custom_runtime_lib", "load_custom_device_plugins"]


def get_available_device():
    return get_all_devices()


def get_available_custom_device():
    return [d for d in get_all_devices() if d.startswith("tpu")]


class cuda:
    """Parity shim for paddle.device.cuda — maps to the TPU accelerator."""

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        import jax
        # block until all dispatched work completes
        (jax.device_put(0) + 0).block_until_ready()

    @staticmethod
    def empty_cache():
        import gc
        gc.collect()

    @staticmethod
    def max_memory_allocated(device=None):
        import jax
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_allocated(device=None):
        import jax
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0
