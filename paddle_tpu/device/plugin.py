"""Custom-device plugin loading (ref: ``LoadCustomRuntimeLib``
``paddle/phi/backends/custom/custom_device.cc:1065,1087`` and the
``CUSTOM_DEVICE_ROOT`` scan in ``paddle/fluid/platform/init.cc:144,240``).

The reference dlopens vendor ``.so`` files implementing its C device ABI
(``device_ext.h``). The TPU-native equivalent of that ABI is PJRT: a
vendor backend ships a PJRT plugin shared library, and registering it
with jax makes its devices first-class (``jax.devices("<name>")``), with
XLA providing the kernel + collective surface the reference's
DeviceInterface/CCL hooks define by hand.
"""
from __future__ import annotations

import glob
import os

__all__ = ["load_custom_runtime_lib", "load_custom_device_plugins",
           "registered_plugins"]

_registered: dict = {}


def registered_plugins():
    return dict(_registered)


def load_custom_runtime_lib(path, name=None):
    """Register one PJRT plugin library with jax.

    path: a ``.so`` file or a directory containing one (the reference
    accepts both, ``custom_device.cc:1087``). name defaults to the
    library basename. Returns the registered plugin name. Must be called
    before the jax backend initializes (same constraint as the
    reference's load-at-init)."""
    if os.path.isdir(path):
        libs = sorted(glob.glob(os.path.join(path, "*.so")))
        if not libs:
            raise FileNotFoundError(
                f"no .so plugin libraries under '{path}'")
        return [load_custom_runtime_lib(p, name=None) for p in libs]
    if not os.path.exists(path):
        raise FileNotFoundError(f"plugin library '{path}' not found")
    plug = name or os.path.splitext(os.path.basename(path))[0]
    plug = plug.removeprefix("lib").removeprefix("pjrt_")
    from jax._src import xla_bridge
    xla_bridge.register_plugin(plug, library_path=path)
    _registered[plug] = path
    return plug


def load_custom_device_plugins(root=None):
    """Scan ``CUSTOM_DEVICE_ROOT`` (or ``root``) for plugin libraries and
    register each — the reference's init-time behavior
    (``init.cc:144``). Missing/empty root is a no-op like the reference.
    Returns the list of registered plugin names."""
    root = root if root is not None else os.environ.get(
        "CUSTOM_DEVICE_ROOT", "")
    if not root or not os.path.isdir(root):
        return []
    out = []
    for lib in sorted(glob.glob(os.path.join(root, "*.so"))):
        try:
            out.append(load_custom_runtime_lib(lib))
        except Exception:
            continue  # a broken vendor lib must not kill init (ref parity)
    return out
