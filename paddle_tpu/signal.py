"""``paddle.signal`` (ref: ``python/paddle/signal.py``): stft / istft built
from framing + ``jnp.fft`` (one fused XLA program; no cuFFT plan cache
needed)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .ops.op_utils import ensure_tensor, nary, unary
from .tensor import Tensor

__all__ = ["stft", "istft", "frame", "overlap_add"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into overlapping frames (ref ``signal.py frame``)."""
    def f(d):
        if axis not in (-1, d.ndim - 1):
            raise NotImplementedError("frame supports the last axis")
        n = d.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(frame_length)[None, :]
               + hop_length * jnp.arange(num)[:, None])
        out = d[..., idx]  # [..., num, frame_length]
        # paddle layout: [..., frame_length, num_frames]
        return jnp.swapaxes(out, -1, -2)
    return unary(f, x, name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (ref ``signal.py overlap_add``)."""
    def f(d):
        # paddle layout: [..., frame_length, num_frames]
        frame_length = d.shape[-2]
        num = d.shape[-1]
        n = frame_length + hop_length * (num - 1)
        frames = jnp.swapaxes(d, -1, -2)  # [..., num, frame_length]
        idx = (jnp.arange(frame_length)[None, :]
               + hop_length * jnp.arange(num)[:, None])  # [num, fl]
        out = jnp.zeros(d.shape[:-2] + (n,), d.dtype)
        return out.at[..., idx].add(frames)
    return unary(f, x, name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (ref ``signal.py stft``).

    Returns [..., n_fft//2+1 (or n_fft), num_frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    if window is None:
        win = jnp.ones(win_length, jnp.float32)
    else:
        win = window._data if isinstance(window, Tensor) else \
            jnp.asarray(window)
    if win_length < n_fft:  # center-pad window to n_fft
        pad = n_fft - win_length
        win = jnp.pad(win, (pad // 2, pad - pad // 2))

    def f(d):
        if center:
            pad = n_fft // 2
            d = jnp.pad(d, [(0, 0)] * (d.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        n = d.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[None, :]
               + hop_length * jnp.arange(num)[:, None])
        frames = d[..., idx] * win  # [..., num, n_fft]
        spec = jnp.fft.rfft(frames, axis=-1) if onesided \
            else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, frames]
    return unary(f, x, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-square normalization (ref ``istft``)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    if window is None:
        win = jnp.ones(win_length, jnp.float32)
    else:
        win = window._data if isinstance(window, Tensor) else \
            jnp.asarray(window)
    if win_length < n_fft:
        pad = n_fft - win_length
        win = jnp.pad(win, (pad // 2, pad - pad // 2))

    def f(d):
        spec = jnp.swapaxes(d, -1, -2)  # [..., frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided \
            else jnp.fft.ifft(spec, axis=-1).real
        frames = frames * win
        num = frames.shape[-2]
        n = n_fft + hop_length * (num - 1)
        idx = (jnp.arange(n_fft)[None, :]
               + hop_length * jnp.arange(num)[:, None])
        out = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        out = out.at[..., idx].add(frames)
        norm = jnp.zeros(n, frames.dtype).at[idx.reshape(-1)].add(
            jnp.tile(win ** 2, num))
        out = out / jnp.maximum(norm, 1e-11)
        if center:
            out = out[..., n_fft // 2: n - n_fft // 2]
        if length is not None:
            if out.shape[-1] < length:  # pad the un-reconstructible tail
                out = jnp.pad(out, [(0, 0)] * (out.ndim - 1)
                              + [(0, length - out.shape[-1])])
            out = out[..., :length]
        return out
    return unary(f, x, name="istft")
