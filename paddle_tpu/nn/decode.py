"""Sequence decoding: BeamSearchDecoder + dynamic_decode + gather_tree
(ref: ``python/paddle/nn/decode.py:153 BeamSearchDecoder``, ``:994
dynamic_decode``; ``paddle/phi/kernels/cpu/gather_tree_kernel.cc``).

TPU design notes:
 - ``gather_tree`` is a reverse ``lax.scan`` over the time axis — one
   compiled backward walk, no per-step host sync.
 - ``dynamic_decode`` drives the beam step from the host with early exit
   when every beam finishes (the idiomatic way to run autoregressive
   decoding against jitted steps); each step itself is pure and traceable,
   so the whole loop can also be captured under ``to_static`` with a fixed
   ``max_step_num``.
"""
from __future__ import annotations

import collections

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..ops.op_utils import ensure_tensor, nary

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode", "gather_tree"]


def gather_tree(ids, parents):
    """Reconstruct full beams from per-step tokens + parent pointers.
    Shapes [max_time, batch, beam_size] (ref ``gather_tree_kernel.cc``)."""
    ids = ensure_tensor(ids)
    parents = ensure_tensor(parents)
    if ids.ndim != 3:
        raise ValueError("gather_tree expects [max_time, batch, beam] ids")

    def f(idv, parv):
        T, B, K = idv.shape

        def step(cur, tp):
            tok, par = tp
            out = jnp.take_along_axis(tok, cur, axis=-1)
            nxt = jnp.take_along_axis(par, cur, axis=-1)
            return nxt, out

        init = jnp.broadcast_to(jnp.arange(K)[None, :], (B, K))
        _, outs = jax.lax.scan(step, init, (idv, parv), reverse=True)
        return outs

    return nary(f, [ids, parents], name="gather_tree")


class Decoder:
    """Abstract decoder interface (ref ``decode.py Decoder``)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN cell (ref ``decode.py:153``).

    The cell is called on [batch*beam, ...] merged tensors; scores,
    predicted ids and parent ids are emitted per step and finalized with
    ``gather_tree``.
    """

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- beam helpers (all pure jnp) ----------------------------------------
    def _merge(self, x):
        """[batch, beam, ...] -> [batch*beam, ...]"""
        s = x.shape
        return x.reshape((s[0] * s[1],) + tuple(s[2:]))

    def _split(self, x):
        s = x.shape
        return x.reshape((s[0] // self.beam_size, self.beam_size)
                         + tuple(s[1:]))

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """Public helper (ref ``decode.py tile_beam_merge_with_batch``):
        tile a [batch, ...] tensor to [batch*beam_size, ...]."""
        x = ensure_tensor(x)
        return nary(lambda d: jnp.repeat(d, beam_size, axis=0), [x],
                    name="tile_beam_merge_with_batch")

    def initialize(self, inits):
        """inits: initial cell states, [batch, ...] leaves."""
        states = jax.tree_util.tree_map(
            lambda t: jnp.repeat(t._data if isinstance(t, Tensor)
                                 else jnp.asarray(t), self.beam_size, axis=0),
            inits, is_leaf=lambda t: isinstance(t, Tensor))
        leaf = jax.tree_util.tree_leaves(states)[0]
        batch = leaf.shape[0] // self.beam_size
        # first beam live (log prob 0), the rest dead (-inf)
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1),
                        jnp.float32)[None, :], (batch, 1))
        init = self.StateWrapper(
            cell_states=states, log_probs=log_probs,
            finished=jnp.zeros((batch, self.beam_size), bool),
            lengths=jnp.zeros((batch, self.beam_size), jnp.int32))
        start = jnp.full((batch, self.beam_size), self.start_token,
                         jnp.int32)
        return start, init, init.finished

    def step(self, time, inputs, states, **kwargs):
        """inputs: [batch, beam] token ids; states: StateWrapper."""
        ids = inputs if not isinstance(inputs, Tensor) else inputs._data
        if self.embedding_fn is not None:
            emb = self.embedding_fn(Tensor(self._merge(ids)))
            emb = emb._data if isinstance(emb, Tensor) else emb
        else:
            emb = self._merge(ids)
        cell_out, next_cell = self.cell(
            Tensor(emb),
            jax.tree_util.tree_map(Tensor, states.cell_states),
            **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = cell_out._data if isinstance(cell_out, Tensor) else cell_out
        next_cell = jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, next_cell,
            is_leaf=lambda t: isinstance(t, Tensor))

        V = logits.shape[-1]
        K = self.beam_size
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = self._split(logp)                       # [B, K, V]
        # finished beams may only emit end_token, at no cost
        fin = states.finished[..., None]
        onehot_end = jax.nn.one_hot(self.end_token, V, dtype=jnp.float32)
        masked = jnp.where(fin, jnp.log(onehot_end + 1e-38)[None, None, :],
                           logp)
        total = states.log_probs[..., None] + masked   # [B, K, V]
        B = total.shape[0]
        flat = total.reshape(B, K * V)
        top_scores, top_idx = jax.lax.top_k(flat, K)
        parent = (top_idx // V).astype(jnp.int32)      # [B, K]
        token = (top_idx % V).astype(jnp.int32)

        def pick_beam(t):
            t = self._split(t)
            picked = jnp.take_along_axis(
                t, parent.reshape(parent.shape + (1,) * (t.ndim - 2)),
                axis=1)
            return self._merge(picked)

        next_cell = jax.tree_util.tree_map(pick_beam, next_cell)
        prev_fin = jnp.take_along_axis(states.finished, parent, axis=1)
        prev_len = jnp.take_along_axis(states.lengths, parent, axis=1)
        now_fin = prev_fin | (token == self.end_token)
        lengths = prev_len + (~prev_fin).astype(jnp.int32)
        next_state = self.StateWrapper(
            cell_states=next_cell, log_probs=top_scores,
            finished=now_fin, lengths=lengths)
        outputs = self.OutputWrapper(scores=top_scores, predicted_ids=token,
                                     parent_ids=parent)
        return outputs, next_state, token, now_fin

    def finalize(self, outputs, final_states, sequence_lengths):
        """outputs: OutputWrapper of [T, B, K] stacks → beams via
        gather_tree."""
        preds = gather_tree(Tensor(outputs.predicted_ids),
                            Tensor(outputs.parent_ids))
        return preds, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run ``decoder`` until every sequence finishes or ``max_step_num``
    (ref ``decode.py:994``). Host-driven loop over pure steps with early
    exit; see module docstring for the TPU stance."""
    if max_step_num is None:
        max_step_num = 256
    inputs, states, finished = decoder.initialize(inits)
    step_outputs = []
    own_lengths = None  # fallback when the decoder's states carry none
    for t in range(int(max_step_num)):
        prev_fin = finished._data if isinstance(finished, Tensor) \
            else jnp.asarray(finished)
        outputs, states, inputs, finished = decoder.step(
            t, inputs, states, **kwargs)
        if impute_finished:
            # reference semantics: steps after a sequence finished emit
            # zeros (so time-reductions over the outputs match)
            def _zero_done(leaf, fin=prev_fin):
                arr = leaf._data if isinstance(leaf, Tensor) else leaf
                f = fin.reshape(fin.shape + (1,) * (arr.ndim - fin.ndim))
                out = jnp.where(f, jnp.zeros((), arr.dtype), arr)
                return Tensor(out) if isinstance(leaf, Tensor) else out
            outputs = jax.tree_util.tree_map(
                _zero_done, outputs, is_leaf=lambda x: isinstance(x, Tensor))
        step_outputs.append(outputs)
        fin = finished._data if isinstance(finished, Tensor) else finished
        fin = jnp.asarray(fin)
        if own_lengths is None:
            own_lengths = jnp.zeros(fin.shape, jnp.int32)
        own_lengths = jnp.where(fin & (own_lengths == 0), t + 1, own_lengths)
        if not isinstance(fin, jax.core.Tracer) and bool(jnp.all(fin)):
            break
    own_lengths = jnp.where(own_lengths == 0, len(step_outputs), own_lengths)

    def _stack(*leaves):
        return jnp.stack([leaf._data if isinstance(leaf, Tensor) else leaf
                          for leaf in leaves])

    # stack the per-step output structures along a new time axis
    stacked = jax.tree_util.tree_map(
        _stack, *step_outputs, is_leaf=lambda t: isinstance(t, Tensor))
    final, final_states = decoder.finalize(stacked, states, None)
    lengths = getattr(final_states, "lengths", own_lengths)
    seq_len = lengths if isinstance(lengths, Tensor) else Tensor(lengths)

    def _batch_major(leaf):
        arr = leaf._data if isinstance(leaf, Tensor) else leaf
        if hasattr(arr, "ndim") and arr.ndim >= 2:
            arr = jnp.swapaxes(arr, 0, 1)
        return Tensor(arr) if isinstance(leaf, Tensor) else arr

    if not output_time_major:
        final = jax.tree_util.tree_map(
            _batch_major, final, is_leaf=lambda t: isinstance(t, Tensor))
    if return_length:
        return final, final_states, seq_len
    return final, final_states
