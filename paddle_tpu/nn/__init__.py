"""``paddle_tpu.nn`` (ref: ``python/paddle/nn/__init__.py``)."""
from .layer.layers import Layer, ParamAttr  # noqa: F401
from .layer.common import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .layer.rnn import RNNCellBase  # noqa: F401
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from . import decode  # noqa: F401
from .clip import (ClipGradByValue, ClipGradByNorm,  # noqa: F401
                   ClipGradByGlobalNorm)
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from . import quant  # noqa: F401
from .layer import layers  # noqa: F401
