"""Gradient clipping (ref: ``python/paddle/nn/clip.py``).

ClipGradByGlobalNorm computes the global norm in one fused XLA reduction
when used inside a jitted train step; eagerly it runs over the tape grads.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        """params_grads: list[(param, grad Tensor|None)] -> same structure."""
        raise NotImplementedError

    # functional form used inside jitted train steps
    def apply_arrays(self, grads: list):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def apply_arrays(self, grads):
        return [None if g is None else jnp.clip(g, self.min, self.max)
                for g in grads]

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply_arrays(self, grads):
        out = []
        for g in grads:
            if g is None:
                out.append(None)
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((g.astype(jnp.float32) * scale).astype(g.dtype))
        return out

    def __call__(self, params_grads):
        arrays = [None if g is None else g._data for _, g in params_grads]
        clipped = self.apply_arrays(arrays)
        return [(p, g if c is None else Tensor(c))
                for (p, g), c in zip(params_grads, clipped)]


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def apply_arrays(self, grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in grads if g is not None]
        if not sq:
            return grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [None if g is None else
                (g.astype(jnp.float32) * scale).astype(g.dtype)
                for g in grads]

    def __call__(self, params_grads):
        arrays = [None if g is None else g._data for _, g in params_grads]
        # respect need_clip (params can opt out, ref ParamAttr.need_clip)
        mask = [getattr(p, "need_clip", True) for p, _ in params_grads]
        sq = [jnp.sum(jnp.square(a.astype(jnp.float32)))
              for a, m in zip(arrays, mask) if a is not None and m]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for (p, g), a, m in zip(params_grads, arrays, mask):
            if a is None or not m:
                out.append((p, g))
            else:
                out.append((p, Tensor(
                    (a.astype(jnp.float32) * scale).astype(a.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """torch-style util the reference also exposes
    (``paddle.nn.utils.clip_grad_norm_``)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    # error_if_nonfinite's API contract IS the host branch+raise;
    # callers opt into the sync explicitly
    # tpu-lint: disable=TPU017
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite gradient norm")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = (p.grad._data.astype(jnp.float32) * scale).astype(
                p.grad._data.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
