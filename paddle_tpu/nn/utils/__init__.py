"""nn.utils (ref: ``python/paddle/nn/utils/``)."""
from ..clip import clip_grad_norm_, clip_grad_value_  # noqa: F401
from ...tensor import Tensor, Parameter

__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters", "weight_norm", "remove_weight_norm",
           "spectral_norm"]


def parameters_to_vector(parameters, name=None):
    from ...ops.manipulation import concat, reshape
    return concat([reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p._data = vec._data[offset:offset + n].reshape(p._data.shape)
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v/||v|| via a forward pre-hook."""
    import jax.numpy as jnp
    w = getattr(layer, name)
    dim_ = dim if dim is not None else -1
    axes = tuple(i for i in range(w.ndim) if i != (dim_ % w.ndim)) \
        if dim is not None else None
    norm = jnp.sqrt(jnp.sum(jnp.square(w._data), axis=axes, keepdims=True)) \
        if axes is not None else jnp.linalg.norm(w._data)
    g = Parameter(norm.squeeze() if axes is not None else norm)
    v = Parameter(w._data)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    del layer._parameters[name]

    def hook(lyr, inputs):
        import jax.numpy as jnp
        vv = lyr._parameters[name + "_v"]
        gg = lyr._parameters[name + "_g"]
        from ...ops.op_utils import nary
        def f(vd, gd):
            nrm = jnp.sqrt(jnp.sum(jnp.square(vd), axis=axes, keepdims=True))
            gshape = list(nrm.shape)
            return vd / nrm * gd.reshape(gshape)
        w_new = nary(f, [vv, gg], name="weight_norm")
        lyr._buffers[name] = w_new
        return None

    layer._buffers[name] = Tensor(w._data)
    layer._non_persistable_buffer_names_set.add(name)
    layer.register_forward_pre_hook(hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    v = layer._parameters.pop(name + "_v", None)
    g = layer._parameters.pop(name + "_g", None)
    if v is not None:
        w = layer._buffers.pop(name, None)
        layer.add_parameter(name, Parameter(
            w._data if w is not None else v._data))
    layer._forward_pre_hooks.clear()
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Apply spectral normalization via forward pre-hook."""
    import numpy as np
    import jax.numpy as jnp
    w = getattr(layer, name)
    d = dim if dim is not None else 0
    h = w.shape[d]

    u0 = np.random.normal(0, 1, h).astype(np.float32)

    def hook(lyr, inputs):
        from ...ops.op_utils import nary
        ww = lyr._parameters.get(name + "_orig")
        def f(wd):
            wm = jnp.moveaxis(wd, d, 0).reshape(wd.shape[d], -1)
            u = jnp.asarray(u0)
            v = None
            for _ in range(n_power_iterations):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return wd / sigma
        lyr._buffers[name] = nary(f, [ww], name="spectral_norm")
        return None

    layer.add_parameter(name + "_orig", Parameter(w._data))
    del layer._parameters[name]
    layer._buffers[name] = Tensor(w._data)
    layer._non_persistable_buffer_names_set.add(name)
    layer.register_forward_pre_hook(hook)
    return layer
