"""Recurrent layers (ref: ``python/paddle/nn/layer/rnn.py``).

TPU-native: the time loop is a ``lax.scan`` — one compiled program, weights
resident in VMEM across steps — instead of the reference's cudnn RNN kernels
or per-step dygraph ops.
"""
from __future__ import annotations

import math
import numpy as np
import jax
import jax.numpy as jnp

from .layers import Layer
from .. import initializer as I
from ...tensor import Tensor
from ...ops.op_utils import nary, ensure_tensor

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        from ...ops.creation import full
        b = batch_ref.shape[batch_dim_idx]
        return full([b, self.hidden_size], init_value, dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        out = nary(f, [ensure_tensor(inputs), ensure_tensor(states),
                       self.weight_ih, self.weight_hh, self.bias_ih,
                       self.bias_hh], name="simple_rnn_cell")
        return out, out

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def f(x, hh, cc, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hh @ wh.T + bh
            i, fgt, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            fgt = jax.nn.sigmoid(fgt)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = fgt * cc + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h_new, c_new = nary(f, [ensure_tensor(inputs), ensure_tensor(h),
                                ensure_tensor(c), self.weight_ih,
                                self.weight_hh, self.bias_ih, self.bias_hh],
                            name="lstm_cell", n_out=2)
        return h_new, (h_new, c_new)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, in_ = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(in_ + r * hn)
            return (1 - z) * n + z * h
        out = nary(f, [ensure_tensor(inputs), ensure_tensor(states),
                       self.weight_ih, self.weight_hh, self.bias_ih,
                       self.bias_hh], name="gru_cell")
        return out, out

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Run a cell over time with lax.scan (ref: rnn.py RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # iterate on host for eager parity; jit users wrap the whole model
        from ...ops.manipulation import stack, flip
        x = inputs
        if not self.time_major:
            from ...ops.manipulation import transpose
            perm = list(range(x.ndim))
            perm[0], perm[1] = 1, 0
            x = transpose(x, perm)
        T = x.shape[0]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = [None] * T
        for t in steps:
            out, states = self.cell(x[t], states)
            outs[t] = out
        y = stack(outs, axis=0)
        if not self.time_major:
            from ...ops.manipulation import transpose
            perm = list(range(y.ndim))
            perm[0], perm[1] = 1, 0
            y = transpose(y, perm)
        return y, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import concat
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        y_fw, s_fw = self.rnn_fw(inputs, st_fw)
        y_bw, s_bw = self.rnn_bw(inputs, st_bw)
        return concat([y_fw, y_bw], axis=-1), (s_fw, s_bw)


class _RNNBase(Layer):
    """Multi-layer (bi)directional rnn built from cells, scan-based."""

    CELL = None

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        from .container import LayerList
        self.cells_fw = LayerList()
        self.cells_bw = LayerList() if self.bidirect else None
        in_sz = input_size
        mult = 2 if self.bidirect else 1
        for i in range(num_layers):
            self.cells_fw.append(self.CELL(
                in_sz, hidden_size, weight_ih_attr=weight_ih_attr,
                weight_hh_attr=weight_hh_attr, bias_ih_attr=bias_ih_attr,
                bias_hh_attr=bias_hh_attr))
            if self.bidirect:
                self.cells_bw.append(self.CELL(
                    in_sz, hidden_size, weight_ih_attr=weight_ih_attr,
                    weight_hh_attr=weight_hh_attr, bias_ih_attr=bias_ih_attr,
                    bias_hh_attr=bias_hh_attr))
            in_sz = hidden_size * mult

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import concat
        from .. import functional as F
        x = inputs
        final_states = []
        for i in range(self.num_layers):
            fw = RNN(self.cells_fw[i], False, self.time_major)
            y_fw, s_fw = fw(x, None)
            if self.bidirect:
                bw = RNN(self.cells_bw[i], True, self.time_major)
                y_bw, s_bw = bw(x, None)
                x = concat([y_fw, y_bw], axis=-1)
                final_states.append((s_fw, s_bw))
            else:
                x = y_fw
                final_states.append(s_fw)
            if self.dropout > 0 and i < self.num_layers - 1:
                x = F.dropout(x, self.dropout, training=self.training)
        return x, self._pack_states(final_states)

    def _pack_states(self, states):
        from ...ops.manipulation import stack
        if isinstance(states[0], tuple) and not isinstance(
                states[0][0], Tensor):
            # bidirect: list of ((h,c)|h pairs)
            flat = []
            for pair in states:
                flat.extend(pair)
            states = flat
        if isinstance(states[0], tuple):  # LSTM (h, c)
            hs = stack([s[0] for s in states], axis=0)
            cs = stack([s[1] for s in states], axis=0)
            return (hs, cs)
        return stack(states, axis=0)


class SimpleRNN(_RNNBase):
    CELL = SimpleRNNCell

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        self._activation = activation
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    CELL = LSTMCell


class GRU(_RNNBase):
    CELL = GRUCell
