"""Pooling layers (ref: ``python/paddle/nn/layer/pooling.py``)."""
from __future__ import annotations

from .layers import Layer
from .. import functional as F

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D", "LPPool1D", "LPPool2D",
           "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D"]


class _Pool(Layer):
    _fn = None
    _extra = {}

    def __init__(self, kernel_size, stride=None, padding=0, **kwargs):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}

    def forward(self, x):
        return getattr(F, self._fn)(x, self.kernel_size, self.stride,
                                    self.padding, **self.kwargs)

    def extra_repr(self):
        return f"kernel_size={self.kernel_size}, stride={self.stride}"


class AvgPool1D(_Pool):
    _fn = "avg_pool1d"


class AvgPool2D(_Pool):
    _fn = "avg_pool2d"


class AvgPool3D(_Pool):
    _fn = "avg_pool3d"


class MaxPool1D(_Pool):
    _fn = "max_pool1d"


class MaxPool2D(_Pool):
    _fn = "max_pool2d"


class MaxPool3D(_Pool):
    _fn = "max_pool3d"


class _AdaptivePool(Layer):
    _fn = None

    def __init__(self, output_size, **kwargs):
        super().__init__()
        self.output_size = output_size
        self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}

    def forward(self, x):
        return getattr(F, self._fn)(x, self.output_size, **self.kwargs)


class AdaptiveAvgPool1D(_AdaptivePool):
    _fn = "adaptive_avg_pool1d"


class AdaptiveAvgPool2D(_AdaptivePool):
    _fn = "adaptive_avg_pool2d"


class AdaptiveAvgPool3D(_AdaptivePool):
    _fn = "adaptive_avg_pool3d"


class AdaptiveMaxPool1D(_AdaptivePool):
    _fn = "adaptive_max_pool1d"


class AdaptiveMaxPool2D(_AdaptivePool):
    _fn = "adaptive_max_pool2d"


class AdaptiveMaxPool3D(_AdaptivePool):
    _fn = "adaptive_max_pool3d"


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        n, k, s, p, c, df = self.args
        return F.lp_pool1d(x, n, k, s, p, c, df)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        n, k, s, p, c, df = self.args
        return F.lp_pool2d(x, n, k, s, p, c, df)


class _MaxUnPool(Layer):
    def __init__(self, n, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self.n = n
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        fn = [F.max_unpool1d, F.max_unpool2d, F.max_unpool3d][self.n - 1]
        return fn(x, indices, self.kernel_size, stride=self.stride,
                  padding=self.padding, data_format=self.data_format,
                  output_size=self.output_size)


class MaxUnPool1D(_MaxUnPool):
    """Inverse of MaxPool1D given return_mask indices (ref
    ``layer/pooling.py:1204`` family)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__(1, kernel_size, stride, padding, data_format,
                         output_size)


class MaxUnPool2D(_MaxUnPool):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__(2, kernel_size, stride, padding, data_format,
                         output_size)


class MaxUnPool3D(_MaxUnPool):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__(3, kernel_size, stride, padding, data_format,
                         output_size)
