"""Activation layers (ref: ``python/paddle/nn/layer/activation.py``)."""
from __future__ import annotations

from .layers import Layer
from .. import functional as F
from .. import initializer as I

__all__ = ["ReLU", "ReLU6", "ELU", "SELU", "CELU", "GELU", "Silu", "Swish",
           "Mish", "Softplus", "Softsign", "Softshrink", "Hardshrink",
           "Tanhshrink", "ThresholdedReLU", "LeakyReLU", "PReLU", "RReLU",
           "Hardtanh", "Hardsigmoid", "Hardswish", "Sigmoid", "LogSigmoid",
           "Tanh", "Softmax", "LogSoftmax", "Maxout", "GLU", "Softmax2D"]


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = {**fixed}
            # positional args map onto the functional's kwargs in order
            names = [k for k in _SIGS.get(fn_name, [])]
            for n, v in zip(names, args):
                self._kwargs[n] = v
            for k, v in kwargs.items():
                if k != "name":
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kwargs)
    _Act.__name__ = fn_name
    return _Act


_SIGS = {
    "elu": ["alpha"], "selu": ["scale", "alpha"], "celu": ["alpha"],
    "gelu": ["approximate"], "softplus": ["beta", "threshold"],
    "softshrink": ["threshold"], "hardshrink": ["threshold"],
    "thresholded_relu": ["threshold", "value"],
    "leaky_relu": ["negative_slope"], "hardtanh": ["min", "max"],
    "hardsigmoid": ["slope", "offset"], "softmax": ["axis"],
    "log_softmax": ["axis"], "maxout": ["groups", "axis"], "glu": ["axis"],
    "rrelu": ["lower", "upper"],
}

ReLU = _simple("relu")
ReLU6 = _simple("relu6")
ELU = _simple("elu")
SELU = _simple("selu")
CELU = _simple("celu")
GELU = _simple("gelu")
Silu = _simple("silu")
Swish = _simple("swish")
Mish = _simple("mish")
Softplus = _simple("softplus")
Softsign = _simple("softsign")
Softshrink = _simple("softshrink")
Hardshrink = _simple("hardshrink")
Tanhshrink = _simple("tanhshrink")
ThresholdedReLU = _simple("thresholded_relu")
LeakyReLU = _simple("leaky_relu")
Hardtanh = _simple("hardtanh")
Hardsigmoid = _simple("hardsigmoid")
Hardswish = _simple("hardswish")
Sigmoid = _simple("sigmoid")
LogSigmoid = _simple("log_sigmoid")
Tanh = _simple("tanh")
Softmax = _simple("softmax")
LogSoftmax = _simple("log_softmax")
Maxout = _simple("maxout")
GLU = _simple("glu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW / CHW inputs (ref
    ``layer/activation.py Softmax2D``)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        from ...ops.op_utils import ensure_tensor
        x = ensure_tensor(x)
        if x.ndim not in (3, 4):
            raise ValueError(
                f"Softmax2D requires a 3D or 4D tensor, got {x.ndim}D")
        from .. import functional as F
        return F.softmax(x, axis=-3)
