"""Layer: the module base class.

TPU-native re-design of the reference ``nn.Layer``
(``python/paddle/nn/layer/layers.py:339``): parameter/buffer/sublayer
registries, hooks, state_dict, train/eval — the module *surface* is kept,
while execution is jax eager ops + tape (no static Program attached).

The extra capability over the reference: any Layer can be captured
functionally (`paddle_tpu.jit.functional_call`) so whole training steps
compile to one XLA program — the design center of the framework.
"""
from __future__ import annotations

import collections
from typing import Callable, Iterator

import numpy as np
import jax.numpy as jnp

from ...tensor import Tensor, Parameter
from ...framework.dtype import to_jax_dtype
from .. import initializer as I

__all__ = ["Layer", "ParamAttr"]


class ParamAttr:
    """Parameter attribute bundle (ref: ``python/paddle/fluid/param_attr.py``)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, bool):
            return ParamAttr(trainable=True) if attr else None
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        raise TypeError(f"Cannot interpret {attr!r} as ParamAttr")


def make_parameter(shape, attr=None, dtype="float32", is_bias=False,
                   default_initializer=None):
    """Shared parameter factory behind ``Layer.create_parameter`` and the
    standalone ``paddle.create_parameter``. Honors ``LazyGuard``: under the
    guard the parameter holds a host-side numpy placeholder (NO device
    allocation) and the initializer runs at ``Parameter.initialize()``."""
    attr = ParamAttr._to_attr(attr)
    if attr is None:
        return None
    init = attr.initializer or default_initializer or (
        I.Constant(0.0) if is_bias else I.XavierNormal())
    from ...framework.lazy_init import lazy_init_active
    if lazy_init_active():
        import numpy as _np
        jdt = to_jax_dtype(dtype)
        try:
            ph_dtype = _np.dtype(jdt)  # bf16/fp16 work via ml_dtypes
        except TypeError:
            ph_dtype = _np.float32
        p = Parameter(_np.zeros((), _np.float32), name=attr.name,
                      trainable=attr.trainable)
        # host placeholder, rebound after ctor so jnp.asarray never runs
        # on the full shape (a model built under the guard must not touch
        # device HBM)
        p._data = _np.zeros(tuple(int(s) for s in shape), dtype=ph_dtype)
        p._lazy = (init, tuple(int(s) for s in shape), jdt)
    else:
        data = init(shape, to_jax_dtype(dtype))
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
    p.optimize_attr = {"learning_rate": attr.learning_rate}
    p.regularizer = attr.regularizer
    p.do_model_average = attr.do_model_average
    p.need_clip = attr.need_clip if hasattr(attr, "need_clip") else True
    return p


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    """Base class for all neural network layers."""

    def __init__(self, name_scope=None, dtype="float32"):
        # use object.__setattr__ to bypass our routing during init
        d = self.__dict__
        d["_parameters"] = collections.OrderedDict()
        d["_buffers"] = collections.OrderedDict()
        d["_non_persistable_buffer_names_set"] = set()
        d["_sub_layers"] = collections.OrderedDict()
        d["_forward_pre_hooks"] = collections.OrderedDict()
        d["_forward_post_hooks"] = collections.OrderedDict()
        d["training"] = True
        d["_dtype"] = dtype
        d["_name_scope"] = name_scope or type(self).__name__.lower()
        d["_hook_id"] = 0

    # -- forward ------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} must implement forward()")

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # -- parameter creation --------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """ref: ``layers.py create_parameter`` — default init is Xavier for
        weights, zeros for bias, matching the reference's defaults."""
        return make_parameter(shape, attr=attr,
                              dtype=dtype or self._dtype or "float32",
                              is_bias=is_bias,
                              default_initializer=default_initializer)

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter or None")
        self._parameters[name] = parameter
        return parameter

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if tensor is not None:
            # scope-resident in static mode (not a baked constant), and
            # included in checkpoints — ref framework.py persistable vars
            tensor.persistable = persistable
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        else:
            self._non_persistable_buffer_names_set.discard(name)
        return tensor

    def add_sublayer(self, name, sublayer):
        if sublayer is not None and not isinstance(sublayer, Layer):
            raise TypeError("add_sublayer expects a Layer or None")
        self._sub_layers[name] = sublayer
        return sublayer

    # -- attribute routing ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            _remove_from(name, buffers, layers, self.__dict__)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning layers")
            _remove_from(name, params, buffers, self.__dict__)
            layers[name] = value
        elif params is not None and name in params:
            params[name] = value
        elif layers is not None and name in layers:
            layers[name] = value
        elif buffers is not None and name in buffers:
            buffers[name] = value
        elif isinstance(value, Tensor) and buffers is not None and \
                not name.startswith("_"):
            # plain tensors assigned as attributes become (persistable)
            # buffers, matching the reference's behavior
            self.__dict__.pop(name, None)
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)

    # -- traversal -----------------------------------------------------------
    def parameters(self, include_sublayers=True) -> list:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True,
                         remove_duplicate=True) -> Iterator:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is None or (remove_duplicate and id(p) in seen):
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers=True) -> list:
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True) -> Iterator:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator:
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False) -> list:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False,
                        layers_set=None) -> Iterator:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True,
                                         layers_set=layers_set)

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else \
            collections.OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in self.named_sublayers(include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names_set:
                    continue
                full = f"{name}.{bname}" if name else bname
                if structured_name_prefix:
                    full = structured_name_prefix + full
                dest[full] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Returns (missing_keys, unexpected_keys) like the reference."""
        own = self.state_dict()
        missing, unexpected = [], []
        for k in own:
            if k not in state_dict:
                missing.append(k)
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            target = own[k]
            arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            if tuple(arr.shape) != tuple(target._data.shape):
                raise ValueError(
                    f"shape mismatch for {k}: loaded {tuple(arr.shape)} vs "
                    f"expected {tuple(target._data.shape)}")
            target._data = arr.astype(target._data.dtype)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- mode / movement -----------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn: Callable):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        import jax
        from ...framework.device import get_jax_device
        dev = get_jax_device(device) if device is not None else None
        dt = to_jax_dtype(dtype) if dtype is not None else None
        for t in list(self.parameters()) + list(self.buffers()):
            d = t._data
            if dt is not None and np.dtype(d.dtype).kind == "f":
                d = d.astype(dt)
            if dev is not None:
                d = jax.device_put(d, dev)
            t._data = d
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- misc ----------------------------------------------------------------
    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self._sub_layers.items():
            mod_str = repr(l)
            mod_str = _addindent(mod_str, 2)
            lines.append(f"({name}): {mod_str}")
        main = type(self).__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


def _remove_from(name, *dicts):
    for d in dicts:
        if d is not None and name in d:
            del d[name]


def _addindent(s, n):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    return lines[0] + "\n" + "\n".join(" " * n + l for l in lines[1:])
