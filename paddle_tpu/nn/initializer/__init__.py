"""Weight initializers (ref: ``python/paddle/nn/initializer/``).

Initializers here are pure functions ``(shape, dtype) -> jax array`` drawing
from the global counter-based RNG — no init ops in a startup Program like
the reference's static path.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework import random as _random
from ...tensor import Tensor

__all__ = [
    "Bilinear",
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain", "set_global_initializer",
]

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(tuple(shape), self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return (jax.random.normal(_random.next_key(), tuple(shape),
                                  dtype=jnp.float32) * self.std
                + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    """Truncated at 2 std like the reference's TruncatedNormalInitializer."""

    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=jnp.float32):
        z = jax.random.truncated_normal(_random.next_key(), self.a, self.b,
                                        tuple(shape), dtype=jnp.float32)
        return (z * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.uniform(_random.next_key(), tuple(shape),
                                  dtype=jnp.float32, minval=self.low,
                                  maxval=self.high).astype(dtype)


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: receptive field * channels.  Our conv weight layout is
    # (out_c, in_c, *spatial) like the reference's NCHW-major layout.
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(_random.next_key(), tuple(shape),
                                  dtype=jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(_random.next_key(), tuple(shape),
                                  dtype=jnp.float32, minval=-limit,
                                  maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return (jax.random.normal(_random.next_key(), tuple(shape),
                                  dtype=jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(_random.next_key(), tuple(shape),
                                  dtype=jnp.float32, minval=-limit,
                                  maxval=limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        v = self.value
        arr = v._data if isinstance(v, Tensor) else jnp.asarray(v)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(tuple(shape))
        return arr.astype(dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        shape = tuple(shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = (rows, cols)
        a = jax.random.normal(_random.next_key(),
                              (max(rows, cols), min(rows, cols)),
                              dtype=jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    """Identity-preserving conv init (ref: nn/initializer/dirac.py)."""

    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        out_c, in_c = shape[0], shape[1]
        spatial = shape[2:]
        w = np.zeros(tuple(shape), dtype=np.float32)
        center = tuple(s // 2 for s in spatial)
        per_group = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per_group, in_c)):
                w[(g * per_group + i, i) + center] = 1.0
        return jnp.asarray(w).astype(dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d",
                        "conv_transpose1d", "conv_transpose2d",
                        "conv_transpose3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3.0
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4.0
    raise ValueError(f"unknown nonlinearity {nonlinearity}")


class Bilinear(Initializer):
    """Bilinear-upsampling kernel initializer for transposed conv
    weights (ref ``nn/initializer/Bilinear.py``): weight[c, 0, i, j] is
    the separable triangle kernel value, so a stride-f Conv2DTranspose
    initialised with it performs bilinear upsampling by factor f."""

    def __call__(self, shape, dtype=jnp.float32):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer requires a 4-D "
                             f"(C_out, C_in/groups, K, K) shape; got {shape}")
        import numpy as np
        k_h, k_w = shape[-2], shape[-1]
        f_h, f_w = (k_h + 1) // 2, (k_w + 1) // 2
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        i = np.arange(k_h)[:, None]
        j = np.arange(k_w)[None, :]
        kern = ((1 - np.abs(i / f_h - c_h))
                * (1 - np.abs(j / f_w - c_w))).astype(np.float32)
        w = np.zeros(shape, np.float32)
        w[...] = kern  # every (c_out, c_in) channel pair gets the kernel
        return jnp.asarray(w).astype(dtype)
