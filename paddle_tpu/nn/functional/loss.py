"""Loss functionals (ref: ``python/paddle/nn/functional/loss.py``).

cross_entropy fuses log_softmax + gather (one XLA computation), the TPU
equivalent of the reference's fused ``softmax_with_cross_entropy`` CUDA
kernel (``paddle/phi/kernels/gpu/cross_entropy_kernel.cu``).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...tensor import Tensor
from ...ops.op_utils import ensure_tensor, nary, unary as _unary

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "cosine_similarity",
    "cosine_embedding_loss", "hinge_embedding_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "ctc_loss", "log_loss",
    "square_error_cost", "sigmoid_focal_loss", "dice_loss",
    "npair_loss", "poisson_nll_loss", "gaussian_nll_loss",
    "multi_label_soft_margin_loss", "soft_margin_loss", "rnnt_loss",
    "margin_cross_entropy", "hsigmoid_loss", "multi_margin_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    fused = _maybe_fused_cross_entropy(
        input, label, weight=weight, ignore_index=ignore_index,
        reduction=reduction, soft_label=soft_label, axis=axis,
        use_softmax=use_softmax, label_smoothing=label_smoothing)
    if fused is not None:
        return fused

    def f(logits, lab, *w):
        ax = axis % logits.ndim
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=ax) \
            if use_softmax else jnp.log(jnp.maximum(
                logits.astype(jnp.float32), 1e-30))
        n_class = logits.shape[ax]
        if soft_label or (lab.ndim == logits.ndim and
                          lab.shape[ax] == n_class and
                          jnp.issubdtype(lab.dtype, jnp.floating)):
            soft = lab.astype(jnp.float32)
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_class
            loss = -jnp.sum(soft * logp, axis=ax)
            if w:
                wvec = w[0].astype(jnp.float32)
                loss = loss * jnp.sum(soft * wvec, axis=ax)
            return _reduce(loss, reduction)
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == logits.ndim:
            lab_i = jnp.squeeze(lab_i, axis=ax)
        onehot_ll = jnp.take_along_axis(
            logp, jnp.expand_dims(jnp.clip(lab_i, 0, n_class - 1), ax),
            axis=ax)
        loss = -jnp.squeeze(onehot_ll, axis=ax)
        if label_smoothing > 0:
            smooth_loss = -jnp.mean(logp, axis=ax)
            loss = (1 - label_smoothing) * loss + label_smoothing * smooth_loss
        valid = (lab_i != ignore_index)
        loss = jnp.where(valid, loss, 0.0)
        if w:
            wvec = w[0].astype(jnp.float32)
            sample_w = jnp.take(wvec, jnp.clip(lab_i, 0, n_class - 1))
            sample_w = jnp.where(valid, sample_w, 0.0)
            loss = loss * sample_w
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(sample_w), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)

    args = [input, label] + ([ensure_tensor(weight)] if weight is not None
                             else [])
    return nary(f, args, name="cross_entropy")


def _maybe_fused_cross_entropy(input, label, *, weight, ignore_index,
                               reduction, soft_label, axis, use_softmax,
                               label_smoothing):
    """Route hard-label cross-entropy through the fused Pallas
    softmax-xent kernel (same gate shape as
    ``scaled_dot_product_attention``: flag + hardware + one-time
    lowering canary, XLA fallback on any failure or ineligible shape).
    Returns the loss Tensor, or None when the caller should take the
    XLA path. Soft labels, class weights, and non-trailing class axes
    stay on XLA."""
    from ...framework import flags as _flags
    from ...ops.fused_kernels import record_dispatch as _record
    try:
        eligible = (use_softmax and not soft_label and weight is None
                    and input.ndim >= 1
                    and axis % input.ndim == input.ndim - 1
                    and not (label.ndim == input.ndim
                             and label.shape[-1] == input.shape[-1]
                             and jnp.issubdtype(label._data.dtype,
                                                jnp.floating))
                    and jnp.issubdtype(label._data.dtype, jnp.integer))
    except Exception:
        eligible = False
    if not (eligible and _flags.flag("use_pallas_kernels")):
        _record("fused_softmax_xent", "fallback")
        return None
    from .common import _on_tpu, _fused_xent_usable
    if not (_on_tpu() and _fused_xent_usable()):
        _record("fused_softmax_xent", "fallback")
        return None

    def f(logits, lab):
        from ...ops.fused_kernels import fused_softmax_xent
        n_class = logits.shape[-1]
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == logits.ndim:
            lab_i = jnp.squeeze(lab_i, axis=-1)
        rows = int(np.prod(lab_i.shape)) if lab_i.ndim else 1
        loss = fused_softmax_xent(
            logits.reshape(rows, n_class), lab_i.reshape(rows),
            ignore_index=ignore_index, label_smoothing=label_smoothing)
        loss = loss.reshape(lab_i.shape)
        if reduction == "mean":
            valid = (lab_i != ignore_index).astype(jnp.float32)
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1.0)
        return _reduce(loss, reduction)

    try:
        out = nary(f, [input, label], name="cross_entropy")
        _record("fused_softmax_xent", "pallas")
        return out
    except Exception:
        _record("fused_softmax_xent", "fallback")
        return None


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from .activation import softmax as _softmax
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def f(p, y, *w):
        p32 = jnp.clip(p.astype(jnp.float32), 1e-12, 1.0 - 1e-7)
        out = -(y * jnp.log(p32) + (1 - y) * jnp.log1p(-p32))
        if w:
            out = out * w[0]
        return _reduce(out, reduction)
    args = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return nary(f, args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def f(z, y, *extra):
        z = z.astype(jnp.float32)
        y = y.astype(jnp.float32)
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]; i += 1
        if pos_weight is not None:
            pw = extra[i]; i += 1
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), pos_weight variant
        if pw is not None:
            log_w = (pw - 1) * y + 1
            out = (1 - y) * z + log_w * (jnp.logaddexp(0.0, -jnp.abs(z))
                                         + jnp.maximum(-z, 0.0))
        else:
            out = jnp.maximum(z, 0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        if w is not None:
            out = out * w
        return _reduce(out, reduction)
    args = [ensure_tensor(logit), ensure_tensor(label)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if pos_weight is not None:
        args.append(ensure_tensor(pos_weight))
    return nary(f, args, name="bce_with_logits")


def mse_loss(input, label, reduction="mean", name=None):
    return nary(lambda a, b: _reduce(jnp.square(a - b), reduction),
                [ensure_tensor(input), ensure_tensor(label)], name="mse_loss")


def square_error_cost(input, label):
    return nary(lambda a, b: jnp.square(a - b),
                [ensure_tensor(input), ensure_tensor(label)],
                name="square_error_cost")


def l1_loss(input, label, reduction="mean", name=None):
    return nary(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                [ensure_tensor(input), ensure_tensor(label)], name="l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def f(logp, lab, *w):
        lab_i = lab.astype(jnp.int32)
        n_class = logp.shape[1]
        ll = jnp.take_along_axis(
            logp, jnp.expand_dims(jnp.clip(lab_i, 0, n_class - 1), 1), axis=1)
        loss = -jnp.squeeze(ll, axis=1)
        valid = lab_i != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if w:
            sw = jnp.take(w[0], jnp.clip(lab_i, 0, n_class - 1))
            sw = jnp.where(valid, sw, 0.0)
            loss = loss * sw
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(sw), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)
    args = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return nary(f, args, name="nll_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        diff = jnp.abs(a - b)
        out = jnp.where(diff < delta, 0.5 * diff * diff / delta,
                        diff - 0.5 * delta)
        return _reduce(out, reduction)
    return nary(f, [ensure_tensor(input), ensure_tensor(label)],
                name="smooth_l1_loss")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(logp, q):
        if log_target:
            out = jnp.exp(q) * (q - logp)
        else:
            out = jnp.where(q > 0, q * (jnp.log(jnp.maximum(q, 1e-30)) - logp),
                            jnp.zeros_like(q))
        if reduction == "batchmean":
            return jnp.sum(out) / logp.shape[0]
        return _reduce(out, reduction)
    return nary(f, [ensure_tensor(input), ensure_tensor(label)], name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def f(a, b, y):
        out = jnp.maximum(-y * (a - b) + margin, 0.0)
        return _reduce(out, reduction)
    return nary(f, [ensure_tensor(input), ensure_tensor(other),
                    ensure_tensor(label)], name="margin_ranking_loss")


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)
    return nary(f, [ensure_tensor(x1), ensure_tensor(x2)],
                name="cosine_similarity")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-8)
        out = jnp.where(y == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(out, reduction)
    return nary(f, [ensure_tensor(input1), ensure_tensor(input2),
                    ensure_tensor(label)], name="cosine_embedding_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(x, y):
        out = jnp.where(y == 1, x, jnp.maximum(margin - x, 0.0))
        return _reduce(out, reduction)
    return nary(f, [ensure_tensor(input), ensure_tensor(label)],
                name="hinge_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return nary(f, [ensure_tensor(input), ensure_tensor(positive),
                    ensure_tensor(negative)], name="triplet_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        dn2 = distance_function(positive, negative)
        from ...ops.math import minimum
        dn = minimum(dn, dn2)
    from ...ops.math import maximum as _max, mean as _mean, sum as _sum
    from ...ops.creation import zeros_like
    out = _max((dp - dn) + margin, zeros_like(dp))
    if reduction == "mean":
        return _mean(out)
    if reduction == "sum":
        return _sum(out)
    return out


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard alpha-recursion in log space over a lax.scan —
    replaces the reference's vendored warpctc (third_party/warpctc)."""
    log_probs = ensure_tensor(log_probs)  # (T, N, C) paddle layout
    labels = ensure_tensor(labels)        # (N, S)
    input_lengths = ensure_tensor(input_lengths)
    label_lengths = ensure_tensor(label_lengths)

    def f(lp, lab, ilen, llen):
        if lp.ndim == 3 and lab.ndim == 2 and lp.shape[1] == lab.shape[0]:
            pass
        T, N, C = lp.shape
        S = lab.shape[1]
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        # extended label seq with blanks: length 2S+1
        ext = jnp.full((N, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        ext_len = 2 * llen.astype(jnp.int32) + 1
        neg_inf = jnp.float32(-1e30)
        # init alpha at t=0
        alpha0 = jnp.full((N, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(N), ext[:, 0]])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(ext_len > 1, lp[0, jnp.arange(N), ext[:, 1]], neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), dtype=bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a_prev = alpha
            a_shift1 = jnp.concatenate(
                [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_step(carry, t):
            alpha, = carry
            new_alpha, _ = step(alpha, lp[t])
            new_alpha = jnp.where((t < ilen)[:, None], new_alpha, alpha)
            return (new_alpha,), None

        (alphaT,), _ = jax.lax.scan(scan_step, (alpha0,), jnp.arange(1, T))
        idx_last = ext_len - 1
        ll_final = jnp.logaddexp(
            jnp.take_along_axis(alphaT, idx_last[:, None], axis=1)[:, 0],
            jnp.take_along_axis(alphaT, jnp.maximum(idx_last - 1, 0)[:, None],
                                axis=1)[:, 0])
        loss = -ll_final
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(llen.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)

    return nary(f, [log_probs, labels, input_lengths, label_lengths],
                name="ctc_loss")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss (ref ``python/paddle/nn/functional/loss.py``
    rnnt_loss backed by vendored ``third_party/warprnnt`` CUDA kernels).

    TPU-native: the transducer forward variable ``alpha[t, u]`` is
    computed as one ``lax.scan`` over time with a nested scan over the
    label axis (the whole lattice compiles into a single XLA program;
    gradients come from jax's AD through the scans, replacing warprnnt's
    hand-written backward kernel).

    input: ``[B, T, U+1, V]`` UNNORMALIZED logits (log_softmax applied
    internally, matching the reference's ``rnnt_loss``). label:
    ``[B, U]`` int. FastEmit regularization weights the emit path by
    ``(1 + fastemit_lambda)`` (Yu et al. 2021's gradient-side scaling
    folded into the recursion).
    """
    NEG = -1e30

    def f(acts, labels, ilen, ulen):
        B, T, U1, V = acts.shape
        U = U1 - 1
        lp = jax.nn.log_softmax(acts.astype(jnp.float32), axis=-1)
        # blank transition from every node; emit prob of the u-th label
        blank_lp = lp[..., blank]                       # [B, T, U+1]
        lab = labels.astype(jnp.int32)                  # [B, U]
        emit_lp = jnp.take_along_axis(
            lp[:, :, :U, :], lab[:, None, :, None], axis=-1)[..., 0]
        emit_lp = emit_lp + jnp.log1p(fastemit_lambda)  # [B, T, U]
        u_idx = jnp.arange(U1)
        u_valid = u_idx[None, :] <= ulen[:, None]       # [B, U+1]

        def row_update(prev_row, t):
            # vertical (blank) moves from the previous time step
            from_top = prev_row + blank_lp[:, t - 1, :]

            def emit_step(carry, u):
                # horizontal (emit) move within the current time step
                left = carry
                here = jnp.logaddexp(from_top[:, u],
                                     left + emit_lp[:, t, u - 1])
                here = jnp.where(u_valid[:, u], here, NEG)
                return here, here

            a0 = jnp.where(u_valid[:, 0], from_top[:, 0], NEG)
            _, rest = jax.lax.scan(emit_step, a0, jnp.arange(1, U1))
            row = jnp.concatenate([a0[None], rest], axis=0).T  # [B, U+1]
            # rows past this sample's input length stay frozen
            keep = (t < ilen)[:, None]
            return jnp.where(keep, row, prev_row), None

        # t = 0 row: only emit moves are possible
        def first_row(carry, u):
            left = carry
            here = jnp.where(u_valid[:, u], left + emit_lp[:, 0, u - 1], NEG)
            return here, here

        a00 = jnp.zeros((B,), jnp.float32)
        _, first_rest = jax.lax.scan(first_row, a00, jnp.arange(1, U1))
        row0 = jnp.concatenate([a00[None], first_rest], axis=0).T
        rowT, _ = jax.lax.scan(row_update, row0, jnp.arange(1, T))
        # terminal: emit the final blank from node (T-1, U)
        alpha_end = jnp.take_along_axis(
            rowT, ulen[:, None], axis=1)[:, 0]
        final_blank = jnp.take_along_axis(
            blank_lp[jnp.arange(B), ilen - 1, :], ulen[:, None],
            axis=1)[:, 0]
        loss = -(alpha_end + final_blank)
        return _reduce(loss, reduction)

    return nary(f, [ensure_tensor(input), ensure_tensor(label),
                    ensure_tensor(input_lengths).astype("int32"),
                    ensure_tensor(label_lengths).astype("int32")],
                name="rnnt_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return nary(f, [ensure_tensor(input), ensure_tensor(label)],
                name="log_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        out = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            out = out / n[0]
        return _reduce(out, reduction)
    args = [ensure_tensor(logit), ensure_tensor(label)]
    if normalizer is not None:
        args.append(ensure_tensor(normalizer))
    return nary(f, args, name="sigmoid_focal_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    def f(p, y):
        y1 = jax.nn.one_hot(y.astype(jnp.int32)[..., 0], p.shape[-1],
                            dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y1, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return nary(f, [ensure_tensor(input), ensure_tensor(label)],
                name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def f(a, p, y):
        sim = a @ p.T
        y = y.reshape(-1)
        tgt = (y[:, None] == y[None, :]).astype(jnp.float32)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        xent = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1)) +
                        jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return xent + reg
    return nary(f, [ensure_tensor(anchor), ensure_tensor(positive),
                    ensure_tensor(labels)], name="npair_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(x, y):
        if log_input:
            out = jnp.exp(x) - y * x
        else:
            out = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * np.pi * (y + epsilon))
            out = out + jnp.where(y > 1, stirling, 0.0)
        return _reduce(out, reduction)
    return nary(f, [ensure_tensor(input), ensure_tensor(label)],
                name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def f(mu, y, var):
        var = jnp.maximum(var, epsilon)
        out = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            out = out + 0.5 * np.log(2 * np.pi)
        return _reduce(out, reduction)
    return nary(f, [ensure_tensor(input), ensure_tensor(label),
                    ensure_tensor(variance)], name="gaussian_nll_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    def f(x, y, *w):
        out = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        out = jnp.mean(out, axis=-1)
        if w:
            out = out * w[0]
        return _reduce(out, reduction)
    args = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return nary(f, args, name="multi_label_soft_margin_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    def f(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return nary(f, [ensure_tensor(input), ensure_tensor(label)],
                name="soft_margin_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace/CosFace combined-margin CE over (possibly class-sharded)
    cosine logits (ref: ``loss.py:2033``; CUDA kernel
    ``margin_cross_entropy_kernel.cu``).

    TP-aware the TPU way: when called inside an ``mp`` shard_map scope the
    class dim is sharded — the margin is applied locally by the rank that
    owns the target class and softmax statistics reduce with pmax/psum,
    mirroring the ParallelCrossEntropy design (never materializes the
    gathered [N, num_classes] logits). ``group=False`` skips communication
    (data-parallel mode).
    """
    logits = ensure_tensor(logits)
    label = ensure_tensor(label)
    from jax import lax
    from ...distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
        _in_axis_scope, _MP)

    ax = group.axis_name if (group not in (None, False)
                             and hasattr(group, "axis_name")) else _MP
    sharded = group is not False and _in_axis_scope(ax)

    def margin_target(tgt_cos):
        # cos(m1*theta + m2) - m3, numerically guarded acos
        theta = jnp.arccos(jnp.clip(tgt_cos, -1.0 + 1e-7, 1.0 - 1e-7))
        return jnp.cos(margin1 * theta + margin2) - margin3

    def f(lg, y):
        if y.ndim == lg.ndim:
            y = y.squeeze(-1)
        lg = lg.astype(jnp.float32)
        n_local = lg.shape[-1]
        if sharded:
            i = lax.axis_index(ax)
            start = i * n_local
        else:
            start = 0
        in_range = (y >= start) & (y < start + n_local)
        local_y = jnp.clip(y - start, 0, n_local - 1)
        onehot = jax.nn.one_hot(local_y, n_local, dtype=bool) \
            & in_range[..., None]
        modified = jnp.where(onehot, margin_target(lg), lg) * scale
        if sharded:
            m = lax.pmax(jnp.max(modified, axis=-1), ax)
            shifted = modified - m[..., None]
            sumexp = lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), ax)
            tgt = jnp.take_along_axis(shifted, local_y[..., None],
                                      axis=-1)[..., 0]
            tgt = lax.psum(jnp.where(in_range, tgt, 0.0), ax)
        else:
            m = jnp.max(modified, axis=-1)
            shifted = modified - m[..., None]
            sumexp = jnp.sum(jnp.exp(shifted), axis=-1)
            tgt = jnp.take_along_axis(shifted, local_y[..., None],
                                      axis=-1)[..., 0]
        loss = (jnp.log(sumexp) - tgt)[..., None]
        softmax = jnp.exp(shifted) / sumexp[..., None]
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        return loss, softmax

    out = nary(f, [logits, label], name="margin_cross_entropy", n_out=2)
    return (out[0], out[1]) if return_softmax else out[0]


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (ref: ``loss.py hsigmoid_loss``; tree
    encoding ``phi/kernels/funcs/matrix_bit_code.h SimpleCode``: class c
    encodes as c + num_classes; node index at bit b is (code>>(b+1))-1,
    branch bit is (code>>b)&1).

    TPU design: the per-sample variable-length tree path is evaluated as a
    fixed ``ceil(log2)`` -deep masked gather+dot — static shapes for XLA;
    ``is_sparse`` is accepted (gathers are already 'sparse' here)."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    args = [input, label, ensure_tensor(weight)]
    has_bias = bias is not None
    if has_bias:
        args.append(ensure_tensor(bias))
    custom = path_table is not None
    if custom != (path_code is not None):
        raise ValueError("path_table and path_code must be given together")
    if custom:
        args += [ensure_tensor(path_table), ensure_tensor(path_code)]
    max_len = int(np.ceil(np.log2(max(num_classes, 2)))) + 1 \
        if not custom else None

    def f(x, y, w, *rest):
        b = rest[0] if has_bias else None
        if y.ndim == 2:
            y = y[..., 0]
        if custom:
            table = rest[-2]
            code_bits = rest[-1]
            node_idx = table.astype(jnp.int32)          # [N, L]
            bits = code_bits.astype(jnp.float32)        # [N, L]
            mask = (node_idx >= 0).astype(jnp.float32)
            node_safe = jnp.maximum(node_idx, 0)
        else:
            code = y.astype(jnp.int32) + num_classes    # [N]
            L = max_len
            bit_pos = jnp.arange(L)                     # [L]
            lengths = jnp.floor(
                jnp.log2(code.astype(jnp.float32))).astype(jnp.int32)
            mask = (bit_pos[None, :] < lengths[:, None]).astype(jnp.float32)
            node_safe = jnp.maximum(
                (code[:, None] >> (bit_pos[None, :] + 1)) - 1, 0)
            bits = ((code[:, None] >> bit_pos[None, :]) & 1).astype(
                jnp.float32)
        wpath = w[node_safe]                            # [N, L, D]
        pre = jnp.einsum("nld,nd->nl", wpath.astype(jnp.float32),
                         x.astype(jnp.float32))
        if b is not None:
            pre = pre + b.reshape(-1)[node_safe]
        # BCE-with-logits against the branch bit, masked over real path
        per_node = jnp.maximum(pre, 0) - pre * bits + jnp.log1p(
            jnp.exp(-jnp.abs(pre)))
        return jnp.sum(per_node * mask, axis=-1, keepdims=True)

    return nary(f, args, name="hsigmoid_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Multi-class margin (hinge) loss (ref: ``loss.py multi_margin_loss``)."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    args = [input, label]
    if weight is not None:
        args.append(ensure_tensor(weight))

    def f(x, y, *w):
        if y.ndim == 2:
            y = y[..., 0]
        C = x.shape[1]
        tgt = jnp.take_along_axis(x, y[:, None], axis=1)
        hinge = jnp.maximum(0.0, margin - tgt + x) ** p
        if w:
            hinge = hinge * w[0][y][:, None]
        hinge = hinge * (1 - jax.nn.one_hot(y, C, dtype=x.dtype))
        return _reduce(jnp.sum(hinge, axis=1) / C, reduction)

    return nary(f, args, name="multi_margin_loss")
