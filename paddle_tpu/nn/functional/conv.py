"""Convolution functionals.

Ref: ``python/paddle/nn/functional/conv.py`` → cudnn kernels.
TPU-native: one ``lax.conv_general_dilated`` per call — XLA tiles it onto
the MXU directly; layout (NCHW vs NHWC) is a compiler concern, not a kernel
zoo (the reference maintains separate cudnn/onednn layouts).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...tensor import Tensor
from ...ops.op_utils import ensure_tensor, nary, maybe_autocast

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _norm_padding(padding, n, data_format):
    """Returns lax-style padding: 'SAME', 'VALID' or [(lo, hi)] * n."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer))
                                 for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1]))
                for i in range(n)]
    # paddle also allows [[0,0],[0,0],[lo,hi],...] including batch/channel
    if len(padding) == n + 2:
        spatial = padding[2:] if data_format[1] == "C" else padding[1:-1]
        return [(int(p[0]), int(p[1])) if isinstance(p, (list, tuple))
                else (int(p), int(p)) for p in spatial]
    raise ValueError(f"bad padding {padding}")


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else \
            ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else \
        ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format,
          opname):
    x, weight = maybe_autocast(opname, ensure_tensor(x), ensure_tensor(weight))
    channel_last = data_format[-1] == "C"
    dn = _dim_numbers(n, channel_last)
    strides = _norm_tuple(stride, n)
    dil = _norm_tuple(dilation, n)
    pad = _norm_padding(padding, n, data_format)

    def f(d, w, *b):
        # our weight layout follows the reference: (out_c, in_c/groups, *k)
        if channel_last:
            w = jnp.moveaxis(w, (0, 1), (-1, -2))  # -> (*k, in, out)
        out = lax.conv_general_dilated(
            d, w, window_strides=strides, padding=pad,
            rhs_dilation=dil, feature_group_count=groups,
            dimension_numbers=lax.conv_dimension_numbers(
                d.shape, w.shape, dn))
        if b:
            bshape = [1] * out.ndim
            bshape[-1 if channel_last else 1] = b[0].size
            out = out + b[0].reshape(bshape).astype(out.dtype)
        return out

    args = [x, weight] + ([ensure_tensor(bias)] if bias is not None else [])
    return nary(f, args, name=opname)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df,
                 "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, n, data_format, output_size, opname):
    """Fractionally-strided conv: dilate the input by `stride` and run a
    regular conv with the kernel flipped and its in/out roles swapped —
    the textbook construction XLA fuses into one conv HLO.

    Reference weight layout: (in_c, out_c/groups, *k)
    (ref: paddle/phi/kernels/impl/conv_transpose_kernel_impl.h).
    """
    x, weight = maybe_autocast(opname, ensure_tensor(x), ensure_tensor(weight))
    channel_last = data_format[-1] == "C"
    strides = _norm_tuple(stride, n)
    dil = _norm_tuple(dilation, n)
    pad = _norm_padding(padding, n, data_format)
    out_pad = _norm_tuple(output_padding, n)
    dn = _dim_numbers(n, channel_last=False)

    def f(d, w, *b):
        if channel_last:
            d = jnp.moveaxis(d, -1, 1)
        c_in = w.shape[0]
        c_out_per_g = w.shape[1]
        k = w.shape[2:]
        # (in, out/g, *k) -> (g, in/g, out/g, *k) -> (g, out/g, in/g, *k)
        #                 -> (out, in/g, *k), then flip spatial
        wg = w.reshape((groups, c_in // groups, c_out_per_g) + k)
        wg = jnp.swapaxes(wg, 1, 2)
        w2 = wg.reshape((groups * c_out_per_g, c_in // groups) + k)
        w2 = jnp.flip(w2, axis=tuple(range(2, w2.ndim)))
        if isinstance(pad, str):
            eff = [dil[i] * (k[i] - 1) for i in range(n)]
            if pad == "SAME":
                raise NotImplementedError(
                    "SAME padding for conv_transpose: pass explicit ints")
            padding_cfg = [(e, e + out_pad[i]) for i, e in enumerate(eff)]
        else:
            padding_cfg = [(dil[i] * (k[i] - 1) - pad[i][0],
                            dil[i] * (k[i] - 1) - pad[i][1] + out_pad[i])
                           for i in range(n)]
        out = lax.conv_general_dilated(
            d, w2, window_strides=(1,) * n, padding=padding_cfg,
            lhs_dilation=strides, rhs_dilation=dil,
            feature_group_count=groups,
            dimension_numbers=lax.conv_dimension_numbers(
                d.shape, w2.shape, dn))
        if b:
            bshape = [1] * out.ndim
            bshape[1] = b[0].size
            out = out + b[0].reshape(bshape).astype(out.dtype)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x, weight] + ([ensure_tensor(bias)] if bias is not None else [])
    out = nary(f, args, name=opname)
    if output_size is not None:
        want = _norm_tuple(output_size, n)
        have = out.shape[2:] if not channel_last else out.shape[1:-1]
        if tuple(have) != tuple(want):
            # pad tail to requested size (paddle allows sizes within stride)
            extra = [w_ - h_ for w_, h_ in zip(want, have)]
            widths = [(0, 0)] * out.ndim
            off = 2 if not channel_last else 1
            for i, e in enumerate(extra):
                widths[off + i] = (0, e)
            from ...ops.manipulation import pad as _pad_op
            flat = []
            for lo, hi in widths:
                flat += [lo, hi]
            out = _pad_op(out, flat)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, df, output_size,
                           "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size,
                           "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size,
                           "conv3d_transpose")
