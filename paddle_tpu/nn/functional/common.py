"""Common functionals: linear, dropout, embedding, one_hot, interpolate,
attention (ref: ``python/paddle/nn/functional/common.py``, ``input.py``,
``extension.py``).

`scaled_dot_product_attention` routes to a Pallas flash-attention kernel on
TPU hardware (the reference's flash_attn CUDA kernel equivalent,
``paddle/phi/kernels/gpu/flash_attn_kernel.cu``) with a pure-XLA fallback.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...tensor import Tensor
from ...ops.op_utils import ensure_tensor, nary, unary as _unary, maybe_autocast
from ...framework import random as _random
from ...framework import flags as _flags

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "feature_alpha_dropout", "embedding", "one_hot", "label_smooth",
    "interpolate", "upsample", "pixel_shuffle", "pixel_unshuffle",
    "channel_shuffle", "unfold", "fold", "bilinear",
    "scaled_dot_product_attention", "pad", "zeropad2d", "cosine_similarity",
    "temporal_shift", "class_center_sample", "sequence_mask",
    "pairwise_distance", "sparse_attention", "diag_embed",
]

from ...ops.manipulation import pad  # noqa: F401  re-export (paddle has F.pad)
from .loss import cosine_similarity  # noqa: F401


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b; weight layout (in, out) like the reference."""
    x, weight = maybe_autocast("linear", ensure_tensor(x),
                               ensure_tensor(weight))

    def f(d, w, *b):
        out = d @ w
        if b:
            out = out + b[0].astype(out.dtype)
        return out
    args = [x, weight] + ([ensure_tensor(bias)] if bias is not None else [])
    return nary(f, args, name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return _unary(lambda d: d * (1 - p), x, name="dropout")
        return x
    if p == 1.0:
        return _unary(lambda d: jnp.zeros_like(d), x, name="dropout")
    key = _random.next_key()
    axes = None
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)

    def f(d):
        shape = list(d.shape)
        if axes is not None:
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, d / (1.0 - p), 0.0).astype(d.dtype)
        return jnp.where(keep, d, 0.0).astype(d.dtype)
    return _unary(f, x, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x
    key = _random.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(d):
        keep = jax.random.bernoulli(key, 1.0 - p, d.shape)
        a = ((1 - p) * (1 + p * alpha_p ** 2)) ** -0.5
        b = -a * alpha_p * p
        return (a * jnp.where(keep, d, alpha_p) + b).astype(d.dtype)
    return _unary(f, x, name="alpha_dropout")


feature_alpha_dropout = alpha_dropout


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Gather rows; `sparse` is accepted for parity (XLA gathers are always
    'sparse' in the sense that matters)."""
    def f(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)
            out = jnp.where(mask[..., None], 0.0, out)
        return out
    return nary(f, [ensure_tensor(x), ensure_tensor(weight)],
                name="embedding")


def one_hot(x, num_classes, name=None):
    return _unary(lambda d: jax.nn.one_hot(d.astype(jnp.int32), num_classes,
                                           dtype=jnp.float32), x,
                  name="one_hot")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(y, *pd):
        k = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / k
    args = [ensure_tensor(label)]
    if prior_dist is not None:
        args.append(ensure_tensor(prior_dist))
    return nary(f, args, name="label_smooth")


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    lengths = ensure_tensor(lengths)
    ml = maxlen or int(np.asarray(lengths._data).max())
    from ...framework.dtype import to_jax_dtype

    def f(l):
        return (jnp.arange(ml)[None, :] < l[..., None]).astype(
            to_jax_dtype(dtype))
    return _unary(f, lengths, name="sequence_mask")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C"
    n_sp = x.ndim - 2
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(v) for v in size.numpy().tolist()]
        out_sz = tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                       for s in (size if isinstance(size, (list, tuple))
                                 else [size] * n_sp))
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else \
            [scale_factor] * n_sp
        in_sp = x.shape[1:-1] if channel_last else x.shape[2:]
        out_sz = tuple(int(s * f) for s, f in zip(in_sp, sf))

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear",
             "cubic": "cubic"}[mode]

    def f(d):
        dd = d if channel_last else jnp.moveaxis(d, 1, -1)
        tgt = (dd.shape[0],) + out_sz + (dd.shape[-1],)
        if jmode == "nearest":
            # paddle nearest uses floor indexing (align_corners=False)
            in_sp = dd.shape[1:-1]
            idx = []
            for i, (o, s) in enumerate(zip(out_sz, in_sp)):
                ratio = s / o
                idx.append(jnp.floor(jnp.arange(o) * ratio).astype(jnp.int32))
            out = dd
            for dim, ind in enumerate(idx):
                out = jnp.take(out, ind, axis=1 + dim)
        else:
            out = jax.image.resize(dd, tgt, method=jmode)
        return out if channel_last else jnp.moveaxis(out, -1, 1)
    return _unary(f, x, name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(d):
        if data_format == "NCHW":
            n, c, h, w = d.shape
            out = d.reshape(n, c // (r * r), r, r, h, w)
            out = out.transpose(0, 1, 4, 2, 5, 3)
            return out.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = d.shape
        out = d.reshape(n, h, w, r, r, c // (r * r))
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h * r, w * r, c // (r * r))
    return _unary(f, x, name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(d):
        if data_format == "NCHW":
            n, c, h, w = d.shape
            out = d.reshape(n, c, h // r, r, w // r, r)
            out = out.transpose(0, 1, 3, 5, 2, 4)
            return out.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = d.shape
        out = d.reshape(n, h // r, r, w // r, r, c)
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h // r, w // r, c * r * r)
    return _unary(f, x, name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(d):
        if data_format == "NCHW":
            n, c, h, w = d.shape
            return d.reshape(n, groups, c // groups, h, w) \
                .transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = d.shape
        return d.reshape(n, h, w, groups, c // groups) \
            .transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return _unary(f, x, name="channel_shuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (ref: F.unfold). Output (N, C*kh*kw, L)."""
    from .conv import _norm_tuple
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    d_ = _norm_tuple(dilations, 2)
    if isinstance(paddings, int):
        p = [(paddings, paddings)] * 2
    elif len(paddings) == 2:
        p = [(paddings[0], paddings[0]), (paddings[1], paddings[1])]
    else:
        p = [(paddings[0], paddings[2]), (paddings[1], paddings[3])]

    def f(x_):
        n, c, h, w = x_.shape
        patches = jax.lax.conv_general_dilated_patches(
            x_, filter_shape=k, window_strides=s, padding=p,
            rhs_dilation=d_, dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # (N, C*kh*kw, oh, ow) -> (N, C*kh*kw, L)
        return patches.reshape(n, c * k[0] * k[1], -1)
    return _unary(f, x, name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im — adjoint of unfold (scatter-add patches)."""
    from .conv import _norm_tuple
    out_sz = _norm_tuple(output_sizes, 2)
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    d_ = _norm_tuple(dilations, 2)
    pd = _norm_tuple(paddings, 2) if not isinstance(paddings, int) else \
        (paddings, paddings)

    def f(col):
        n, ckk, L = col.shape
        c = ckk // (k[0] * k[1])
        oh = (out_sz[0] + 2 * pd[0] - d_[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (out_sz[1] + 2 * pd[1] - d_[1] * (k[1] - 1) - 1) // s[1] + 1
        col6 = col.reshape(n, c, k[0], k[1], oh, ow)
        out = jnp.zeros((n, c, out_sz[0] + 2 * pd[0], out_sz[1] + 2 * pd[1]),
                        dtype=col.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                hi = i * d_[0]
                wj = j * d_[1]
                out = out.at[:, :, hi:hi + oh * s[0]:s[0],
                             wj:wj + ow * s[1]:s[1]].add(col6[:, :, i, j])
        return out[:, :, pd[0]:pd[0] + out_sz[0], pd[1]:pd[1] + out_sz[1]]
    return _unary(f, x, name="fold")


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *bs):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bs:
            out = out + bs[0]
        return out
    args = [ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return nary(f, args, name="bilinear")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    from ...ops.manipulation import pad as _pad
    return _pad(x, padding, mode="constant", value=0.0,
                data_format=data_format)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    def f(d):
        if data_format == "NHWC":
            d = jnp.moveaxis(d, -1, 1)
        nt, c, h, w = d.shape
        n = nt // seg_num
        v = d.reshape(n, seg_num, c, h, w)
        fold_c = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold_c],
                                jnp.zeros_like(v[:, :1, :fold_c])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold_c:2 * fold_c]),
                                 v[:, :-1, fold_c:2 * fold_c]], axis=1)
        rest = v[:, :, 2 * fold_c:]
        out = jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    return _unary(f, x, name="temporal_shift")


def class_center_sample(label, num_classes, num_samples, group=None):
    """Partial-FC style sampling (host-side, eager only)."""
    label = ensure_tensor(label)
    lab = np.asarray(label._data).ravel()
    pos = np.unique(lab)
    if pos.size >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        extra = np.random.choice(rest, num_samples - pos.size, replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones(num_classes, dtype=np.int64)
    remap[sampled] = np.arange(sampled.size)
    return (Tensor(jnp.asarray(remap[lab].astype(np.int32))),
            Tensor(jnp.asarray(sampled.astype(np.int32))))


# -- attention --------------------------------------------------------------
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Flash attention. Layout (B, S, H, D) — paddle convention.

    On TPU hardware uses the Pallas splash/flash kernel
    (paddle_tpu.ops.pallas_ops); elsewhere an XLA softmax attention whose
    intermediates fuse well (still O(S^2) memory without the kernel).
    """
    q, k_, v = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    q, k_, v = maybe_autocast("matmul", q, k_, v)

    # canary last: it compiles a kernel, so only probe when the Pallas
    # path is actually reachable for this call. Short sequences stay on
    # XLA: its fused attention wins below ~flash_min_seq (the kernel's
    # padding + grid overhead outweighs the O(S^2) saving).
    use_pallas = (attn_mask is None
                  and q.shape[1] >= int(_flags.flag("flash_min_seq"))
                  and _flags.flag("use_pallas_kernels")
                  and _on_tpu() and _flash_usable())
    eff_drop = dropout_p if training else 0.0
    from ...ops.fused_kernels import record_dispatch as _record
    if use_pallas:
        try:
            from ...ops.pallas_ops import flash_attention as _fa
            out = _fa(q, k_, v, causal=is_causal, dropout_p=eff_drop)
            _record("flash_mha", "pallas")
            return out
        except Exception:
            pass  # fall back to XLA path
    _record("flash_mha", "fallback")

    key_rng = _random.next_key() if (dropout_p > 0.0 and training) else None

    def f(qd, kd, vd, *m):
        scale = 1.0 / np.sqrt(qd.shape[-1])
        # (B,S,H,D) -> (B,H,S,D)
        qt = jnp.swapaxes(qd, 1, 2)
        kt = jnp.swapaxes(kd, 1, 2)
        vt = jnp.swapaxes(vd, 1, 2)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
        if is_causal:
            S, K = logits.shape[-2], logits.shape[-1]
            mask = jnp.tril(jnp.ones((S, K), dtype=bool))
            logits = jnp.where(mask, logits, -jnp.inf)
        if m:
            mm = m[0]
            if mm.dtype == jnp.bool_:
                logits = jnp.where(mm, logits, -jnp.inf)
            else:
                logits = logits + mm.astype(logits.dtype)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
            qd.dtype)
        if key_rng is not None:
            keep = jax.random.bernoulli(key_rng, 1 - dropout_p, probs.shape)
            probs = jnp.where(keep, probs / (1 - dropout_p), 0.0)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
        return jnp.swapaxes(out, 1, 2)

    args = [q, k_, v]
    if attn_mask is not None:
        args.append(ensure_tensor(attn_mask))
    return nary(f, args, name="scaled_dot_product_attention")


_CANARY_CACHE: dict = {}


def _kernel_canary(key, probe):
    """One-time eager canary compile+run of a kernel configuration.

    A kernel that traces fine can still fail at LOWERING time, which
    under ``jax.jit`` happens outside any try/except at the call site and
    would kill the whole compiled train step (exactly how the r03 bench
    lost its GPT number) — so probe eagerly once and cache the verdict
    per ``key``. ``probe`` returns arrays to block on."""
    if key not in _CANARY_CACHE:
        try:
            jax.block_until_ready(probe())
            _CANARY_CACHE[key] = True
        except Exception:
            _CANARY_CACHE[key] = False
    return _CANARY_CACHE[key]


def _flash_usable():
    def probe():
        from ...ops.pallas_ops import mha
        x = jnp.zeros((1, 1, 128, 64), jnp.bfloat16)
        # exercise every lowering variant a train step can hit:
        # fwd, fwd+dropout (SMEM seed path), and both bwd kernels
        out = mha(x, x, x, causal=True, interpret=False)
        seed = jnp.ones((), jnp.float32)
        outd = mha(x, x, x, causal=True, dropout_p=0.1, seed=seed,
                   interpret=False)
        g = jax.grad(lambda q: mha(
            q, x, x, causal=True, dropout_p=0.1, seed=seed,
            interpret=False).astype(jnp.float32).sum())(x)
        return out, outd, g
    return _kernel_canary("flash_mha", probe)


def _fused_ln_usable():
    def probe():
        from ...ops.fused_kernels import fused_layer_norm
        x = jnp.zeros((8, 256), jnp.bfloat16)
        w = jnp.ones((256,), jnp.bfloat16)
        b = jnp.zeros((256,), jnp.bfloat16)
        out = fused_layer_norm(x, w, b, interpret=False)
        g = jax.grad(lambda a: fused_layer_norm(
            a, w, b, interpret=False).astype(jnp.float32).sum())(x)
        return out, g
    return _kernel_canary("fused_layer_norm", probe)


def _fused_xent_usable():
    def probe():
        from ...ops.fused_kernels import fused_softmax_xent
        x = jnp.zeros((8, 384), jnp.float32)
        y = jnp.zeros((8,), jnp.int32)
        loss = fused_softmax_xent(x, y, interpret=False)
        g = jax.grad(lambda a: fused_softmax_xent(a, y,
                                                  interpret=False).sum())(x)
        return loss, g
    return _kernel_canary("fused_softmax_xent", probe)


def _on_tpu():
    try:
        return jax.devices()[0].platform not in ("cpu",)
    except RuntimeError:
        return False


from ...ops.creation import diag_embed  # noqa: F401,E402  (F.diag_embed parity)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """p-norm of (x - y + eps) along the last axis (ref
    ``nn/functional/distance.py pairwise_distance``)."""
    import math as _math

    def f(a, b):
        d = a - b + epsilon
        # p is the host-side norm order (a python scalar), not a
        # device value — no transfer happens here
        # tpu-lint: disable=TPU017
        if _math.isinf(float(p)):
            out = jnp.max(jnp.abs(d), axis=-1, keepdims=keepdim) \
                if p > 0 else jnp.min(jnp.abs(d), axis=-1, keepdims=keepdim)
        elif p == 0:
            out = jnp.sum((d != 0).astype(d.dtype), axis=-1,
                          keepdims=keepdim)
        else:
            out = jnp.sum(jnp.abs(d) ** p, axis=-1,
                          keepdims=keepdim) ** (1.0 / p)
        return out
    return nary(f, [ensure_tensor(x), ensure_tensor(y)],
                name="pairwise_distance")


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention with a CSR sparsity pattern (ref
    ``nn/functional/sparse_attention.py``; CUDA kernel
    ``sparse_attention_kernel.cu``).

    TPU realization: the CSR pattern is expanded to a boolean mask and the
    computation runs as masked dense attention — XLA has no CSR-gather
    attention primitive, and for the seq lengths this op targets the MXU
    prefers the dense masked form. Same results as the reference kernel.
    """
    q = ensure_tensor(query)
    k_ = ensure_tensor(key)
    v = ensure_tensor(value)
    offs = ensure_tensor(sparse_csr_offset)
    cols = ensure_tensor(sparse_csr_columns)
    args = [q, k_, v, offs, cols]
    if key_padding_mask is not None:
        args.append(ensure_tensor(key_padding_mask))
    if attn_mask is not None:
        args.append(ensure_tensor(attn_mask))

    def f(qd, kd, vd, od, cd, *masks):
        B, H, S, D = qd.shape
        scale = 1.0 / np.sqrt(D)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qd, kd) * scale

        def fill(bh_cols, bh_offsets):
            # CSR -> dense bool [S, S]: one O(nnz) scatter; entry i
            # belongs to the row r with offsets[r] <= i < offsets[r+1]
            nnz = bh_cols.shape[0]
            pos = jnp.arange(nnz)
            rows = jnp.searchsorted(bh_offsets, pos, side="right") - 1
            valid = pos < bh_offsets[-1]
            m = jnp.zeros((S, S), bool)
            return m.at[jnp.clip(rows, 0, S - 1),
                        jnp.clip(bh_cols, 0, S - 1)].max(valid)

        mask = jax.vmap(jax.vmap(fill))(cd, od)
        neg = jnp.asarray(-1e9, logits.dtype)
        logits = jnp.where(mask, logits, neg)
        mi = 0
        if key_padding_mask is not None:
            kp = masks[mi]
            mi += 1
            logits = jnp.where(kp[:, None, None, :] != 0, logits, neg)
        if attn_mask is not None:
            # paddle semantics: 0 -> masked out (same rule as
            # key_padding_mask), not an additive bias
            am = masks[mi]
            logits = jnp.where(am[None, None, :, :] != 0 if am.ndim == 2
                               else am != 0, logits, neg)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
            qd.dtype)
        probs = jnp.where(mask, probs, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, vd)

    return nary(f, args, name="sparse_attention")
