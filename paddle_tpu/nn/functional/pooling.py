"""Pooling functionals (ref: ``python/paddle/nn/functional/pooling.py``).

All pooling maps to ``lax.reduce_window`` — one HLO, fused by XLA.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...tensor import Tensor
from ...ops.op_utils import ensure_tensor, unary as _unary, nary
from .conv import _norm_tuple, _norm_padding

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d", "lp_pool1d", "lp_pool2d",
    "max_unpool1d", "max_unpool2d", "max_unpool3d",
]


def _pool(x, kernel, stride, padding, n, data_format, reducer, init,
          opname, ceil_mode=False, exclusive=True, divisor_override=None):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C"
    k = _norm_tuple(kernel, n)
    s = _norm_tuple(stride if stride is not None else kernel, n)
    pad = _norm_padding(padding, n, data_format)
    if isinstance(pad, str):
        pad_cfg = pad
    else:
        pad_cfg = pad

    def f(d):
        if channel_last:
            d = jnp.moveaxis(d, -1, 1)
        window = (1, 1) + k
        strides = (1, 1) + s
        if isinstance(pad_cfg, str):
            padding_full = pad_cfg
        else:
            padding_full = [(0, 0), (0, 0)] + list(pad_cfg)
            if ceil_mode:
                padding_full = [(lo, hi + st - 1) if i >= 2 else (lo, hi)
                                for i, ((lo, hi), st) in
                                enumerate(zip(padding_full, strides))]
        if reducer == "max":
            # NB: numpy's dtype.kind is 'V' for bfloat16 — use issubdtype.
            out = lax.reduce_window(d, -jnp.inf
                                    if jnp.issubdtype(d.dtype, jnp.floating)
                                    else jnp.iinfo(d.dtype).min,
                                    lax.max, window, strides, padding_full)
        else:  # avg
            summed = lax.reduce_window(d, 0.0, lax.add, window, strides,
                                       padding_full)
            if divisor_override:
                out = summed / divisor_override
            elif exclusive and (isinstance(pad_cfg, str) or
                                any(p != (0, 0) for p in pad_cfg)) :
                ones = jnp.ones_like(d)
                counts = lax.reduce_window(ones, 0.0, lax.add, window,
                                           strides, padding_full)
                out = summed / counts
            else:
                out = summed / float(np.prod(k))
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return _unary(f, x, name=opname)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _pool(x, kernel_size, stride, padding, 1, df, "avg", 0.0,
                 "avg_pool1d", ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg", 0.0,
                 "avg_pool2d", ceil_mode, exclusive, divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg", 0.0,
                 "avg_pool3d", ceil_mode, exclusive, divisor_override)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    out = _pool(x, kernel_size, stride, padding, 1, df, "max", None,
                "max_pool1d", ceil_mode)
    if return_mask:
        return out, _pool_argmax(x, kernel_size, stride, padding, 1, df,
                                 ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, data_format, "max", None,
                "max_pool2d", ceil_mode)
    if return_mask:
        return out, _pool_argmax(x, kernel_size, stride, padding, 2,
                                 data_format, ceil_mode)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, data_format, "max", None,
                "max_pool3d", ceil_mode)
    if return_mask:
        return out, _pool_argmax(x, kernel_size, stride, padding, 3,
                                 data_format, ceil_mode)
    return out


def _pool_argmax(x, kernel, stride, padding, n, data_format, ceil_mode):
    """Flat indices of max elements (paddle return_mask semantics)."""
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C"
    k = _norm_tuple(kernel, n)
    s = _norm_tuple(stride if stride is not None else kernel, n)
    pad = _norm_padding(padding, n, data_format)

    def f(d):
        # indices are integral metadata — never differentiate through the
        # (value, index) reduce_window (its tuple form has no JVP rule)
        d = jax.lax.stop_gradient(d)
        if channel_last:
            d = jnp.moveaxis(d, -1, 1)
        spatial = d.shape[2:]
        flat_idx = jnp.arange(int(np.prod(spatial))).reshape(spatial)
        flat_idx = jnp.broadcast_to(flat_idx, d.shape)
        window = (1, 1) + k
        strides = (1, 1) + s
        padding_full = pad if isinstance(pad, str) else \
            [(0, 0), (0, 0)] + list(pad)

        def select(a, b):
            av, ai = a
            bv, bi = b
            pick = av >= bv
            return jnp.where(pick, av, bv), jnp.where(pick, ai, bi)
        init = (jnp.asarray(-jnp.inf if jnp.issubdtype(d.dtype, jnp.floating)
                            else jnp.iinfo(d.dtype).min, d.dtype),
                jnp.asarray(-1, jnp.int32))
        _, idx = lax.reduce_window(
            (d, flat_idx.astype(jnp.int32)), init,
            lambda a, b: select(a, b), window, strides, padding_full)
        if channel_last:
            idx = jnp.moveaxis(idx, 1, -1)
        return idx
    return _unary(f, x, name="max_pool_mask")


def _adaptive(x, output_size, n, data_format, mode, opname, return_mask=False):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C"
    out_sz = _norm_tuple(output_size, n)

    def f(d):
        if channel_last:
            d = jnp.moveaxis(d, -1, 1)
        in_sz = d.shape[2:]
        # adaptive pooling: each output cell covers [floor(i*in/out),
        # ceil((i+1)*in/out)) — implement via mean/max over gathered slices
        out = d
        for dim in range(n):
            isz, osz = in_sz[dim], out_sz[dim]
            starts = [int(np.floor(i * isz / osz)) for i in range(osz)]
            ends = [int(np.ceil((i + 1) * isz / osz)) for i in range(osz)]
            segs = []
            for st, en in zip(starts, ends):
                sl = lax.slice_in_dim(out, st, en, axis=2 + dim)
                if mode == "avg":
                    segs.append(jnp.mean(sl, axis=2 + dim, keepdims=True))
                else:
                    segs.append(jnp.max(sl, axis=2 + dim, keepdims=True))
            out = jnp.concatenate(segs, axis=2 + dim)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return _unary(f, x, name=opname)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "NCW", "avg", "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, data_format, "avg",
                     "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, data_format, "avg",
                     "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 1, "NCW", "max", "adaptive_max_pool1d")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 2, "NCHW", "max", "adaptive_max_pool2d")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 3, "NCDHW", "max", "adaptive_max_pool3d")
    return (out, None) if return_mask else out


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    p = float(norm_type)
    from ...ops import math as M
    xe = M.pow(M.abs(ensure_tensor(x)), p)
    pooled = avg_pool1d(xe, kernel_size, stride, padding, exclusive=False,
                        ceil_mode=ceil_mode, data_format=data_format)
    k = _norm_tuple(kernel_size, 1)
    return M.pow(M.multiply(pooled, float(np.prod(k))), 1.0 / p)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)
    from ...ops import math as M
    xe = M.pow(M.abs(ensure_tensor(x)), p)
    pooled = avg_pool2d(xe, kernel_size, stride, padding, exclusive=False,
                        ceil_mode=ceil_mode, data_format=data_format)
    k = _norm_tuple(kernel_size, 2)
    return M.pow(M.multiply(pooled, float(np.prod(k))), 1.0 / p)


def _max_unpool(x, indices, kernel, stride, padding, n, output_size,
                data_format, opname):
    """Inverse of max_pool with return_mask (ref ``pooling.py:1204``
    MaxUnPool): scatter pooled values back to their argmax positions —
    one XLA scatter over the flattened spatial dims."""
    x = ensure_tensor(x)
    indices = ensure_tensor(indices)
    channel_last = data_format[-1] == "C"
    k = _norm_tuple(kernel, n)
    s = _norm_tuple(stride if stride is not None else kernel, n)
    p = _norm_tuple(padding, n)
    in_spatial = (x.shape[1:-1] if channel_last else x.shape[2:])
    if output_size is None:
        out_spatial = tuple(
            (i - 1) * st + kk - 2 * pp
            for i, st, kk, pp in zip(in_spatial, s, k, p))
    else:
        out_spatial = tuple(output_size)[-n:]

    def f(d, idx):
        if channel_last:
            d = jnp.moveaxis(d, -1, 1)
            idx = jnp.moveaxis(idx, -1, 1)
        N, C = d.shape[:2]
        flat_out = int(np.prod(out_spatial))
        dv = d.reshape(N, C, -1)
        iv = idx.reshape(N, C, -1).astype(jnp.int32)
        out = jnp.zeros((N, C, flat_out), d.dtype)
        out = jax.vmap(jax.vmap(
            lambda o, i, v: o.at[i].set(v)))(out, iv, dv)
        out = out.reshape((N, C) + out_spatial)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return nary(f, [x, indices], name=opname)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 1,
                       output_size, "NCW" if data_format in ("NCL", "NCW")
                       else "NWC", "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 2,
                       output_size, data_format, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 3,
                       output_size, data_format, "max_unpool3d")
