"""Normalization functionals (ref: ``python/paddle/nn/functional/norm.py``).

Batch norm's running-stat update mutates the passed mean/variance tensors
in eager mode (matching the reference's in-place running stats); under a
functional trace the updated values propagate through the buffer-threading
machinery in ``paddle_tpu.jit``.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...tensor import Tensor
from ...ops.op_utils import ensure_tensor, nary, unary as _unary

__all__ = ["batch_norm", "layer_norm", "fused_add_layer_norm",
           "instance_norm", "group_norm", "local_response_norm",
           "normalize", "rms_norm"]


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C" and x.ndim > 2
    ch_axis = x.ndim - 1 if channel_last else (1 if x.ndim > 1 else 0)
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    rm, rv = ensure_tensor(running_mean), ensure_tensor(running_var)

    symbolic = not isinstance(x._data, (jax.Array, jax.core.Tracer))

    def f(d, m, v, *wb):
        shape = [1] * d.ndim
        shape[ch_axis] = d.shape[ch_axis]
        out = (d - m.reshape(shape)) * jax.lax.rsqrt(
            v.reshape(shape).astype(d.dtype) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape).astype(d.dtype)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape).astype(d.dtype)
        return out

    if use_batch_stats and symbolic:
        # static-graph mode: batch stats fold into the recorded op; running
        # stats are not threaded through the Program (the reference's static
        # BN updates them via in-place ops in the scope — here inference
        # graphs should be built with is_test/eval so global stats are used)
        def f_sym(d, *wb):
            return f(d, jnp.mean(d, axis=reduce_axes),
                     jnp.var(d, axis=reduce_axes), *wb)

        args = [x]
        if weight is not None:
            args.append(ensure_tensor(weight))
        if bias is not None:
            args.append(ensure_tensor(bias))
        return nary(f_sym, args, name="batch_norm")

    if use_batch_stats:
        # compute batch stats, update running stats (eager mutation)
        def stats(d):
            m = jnp.mean(d, axis=reduce_axes)
            v = jnp.var(d, axis=reduce_axes)
            return m, v
        m_arr, v_arr = stats(x._data)
        # paddle: running = momentum*running + (1-momentum)*batch
        rm._data = momentum * rm._data + (1 - momentum) * m_arr
        n = x.size // x.shape[ch_axis]
        unbiased = v_arr * (n / max(n - 1, 1))
        rv._data = momentum * rv._data + (1 - momentum) * unbiased
        mean_t = Tensor(m_arr)
        var_t = Tensor(v_arr)
    else:
        mean_t, var_t = rm, rv

    args = [x, mean_t, var_t]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return nary(f, args, name="batch_norm")


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               residual=None, name=None):
    """Layer norm; ``residual`` adds a same-shape tensor to ``x`` before
    normalization so the add+LN pair lowers as one fused cluster (the
    residual sum is not rematerialized between the add and the stats)."""
    x = ensure_tensor(x)
    if residual is not None:
        residual = ensure_tensor(residual)
    if isinstance(normalized_shape, (int, np.integer)):
        normalized_shape = (int(normalized_shape),)
    n_axes = len(tuple(normalized_shape))
    axes = tuple(range(x.ndim - n_axes, x.ndim))

    # fused Pallas path (same gate shape as scaled_dot_product_attention:
    # flag + hardware + one-time lowering canary, XLA fallback on any
    # failure); the kernel normalizes a flattened (rows, d) view
    from ...framework import flags as _flags
    from ...ops.fused_kernels import record_dispatch as _record
    use_fused = False
    if _flags.flag("use_pallas_kernels") and x.ndim >= n_axes > 0:
        from .common import _on_tpu, _fused_ln_usable
        use_fused = _on_tpu() and _fused_ln_usable()
    if use_fused:
        d = int(np.prod(tuple(normalized_shape)))

        def f_fused(dd, *rest):
            from ...ops.fused_kernels import fused_layer_norm
            rows = int(np.prod(dd.shape[:dd.ndim - n_axes])) \
                if dd.ndim > n_axes else 1
            i = 0
            r2 = w2 = b2 = None
            if residual is not None:
                r2, i = rest[i].reshape(rows, d), i + 1
            if weight is not None:
                w2, i = rest[i].reshape(d), i + 1
            if bias is not None:
                b2 = rest[i].reshape(d)
            out = fused_layer_norm(dd.reshape(rows, d), w2, b2,
                                   residual=r2, epsilon=epsilon)
            return out.reshape(dd.shape)

        args = [x]
        if residual is not None:
            args.append(residual)
        if weight is not None:
            args.append(ensure_tensor(weight))
        if bias is not None:
            args.append(ensure_tensor(bias))
        try:
            out = nary(f_fused, args, name="layer_norm")
            _record("fused_layer_norm", "pallas")
            return out
        except Exception:
            pass  # fall back to XLA path
    _record("fused_layer_norm", "fallback")

    def f(d, *rest):
        i = 0
        if residual is not None:
            d = d + rest[i].astype(d.dtype)
            i += 1
        m = jnp.mean(d.astype(jnp.float32), axis=axes, keepdims=True)
        v = jnp.var(d.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((d.astype(jnp.float32) - m) * jax.lax.rsqrt(v + epsilon))
        out = out.astype(d.dtype)
        if weight is not None:
            out = out * rest[i].astype(d.dtype)
            i += 1
        if bias is not None:
            out = out + rest[i].astype(d.dtype)
        return out

    args = [x]
    if residual is not None:
        args.append(residual)
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return nary(f, args, name="layer_norm")


def fused_add_layer_norm(x, residual, normalized_shape, weight=None,
                         bias=None, epsilon=1e-5, name=None):
    """Residual-add + layer norm as one op (``y = LN(x + residual)``).

    Thin named entry over ``layer_norm(..., residual=...)`` — the form the
    TPU016 lint rule rewrites manually-composed ``add``/``layer_norm``
    pairs into, and the form the graph-level fusion pass recognizes
    without needing the adjacent-eqn dataflow check to succeed.
    """
    return layer_norm(x, normalized_shape, weight=weight, bias=bias,
                      epsilon=epsilon, residual=residual, name=name)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm — the LLM-era norm; fp32 accumulation, bf16 in/out."""
    def f(d, *w):
        x32 = d.astype(jnp.float32)
        ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        out = (x32 * jax.lax.rsqrt(ms + epsilon)).astype(d.dtype)
        if w:
            out = out * w[0].astype(d.dtype)
        return out
    args = [ensure_tensor(x)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return nary(f, args, name="rms_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C" and x.ndim > 2
    ch_axis = x.ndim - 1 if channel_last else 1
    spatial = tuple(i for i in range(2, x.ndim)) if not channel_last else \
        tuple(i for i in range(1, x.ndim - 1))

    def f(d, *wb):
        m = jnp.mean(d, axis=spatial, keepdims=True)
        v = jnp.var(d, axis=spatial, keepdims=True)
        out = (d - m) * jax.lax.rsqrt(v + eps)
        i = 0
        if weight is not None:
            shape = [1] * d.ndim
            shape[ch_axis] = d.shape[ch_axis]
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            shape = [1] * d.ndim
            shape[ch_axis] = d.shape[ch_axis]
            out = out + wb[i].reshape(shape)
        return out

    args = [x]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return nary(f, args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C" and x.ndim > 2
    def f(d, *wb):
        dd = jnp.moveaxis(d, -1, 1) if channel_last else d
        N, C = dd.shape[0], dd.shape[1]
        rest = dd.shape[2:]
        g = dd.reshape((N, num_groups, C // num_groups) + rest)
        axes = tuple(range(2, g.ndim))
        m = jnp.mean(g, axis=axes, keepdims=True)
        v = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - m) * jax.lax.rsqrt(v + epsilon)).reshape(dd.shape)
        shape = [1] * dd.ndim
        shape[1] = C
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return nary(f, args, name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C"

    def f(d):
        dd = jnp.moveaxis(d, -1, 1) if channel_last else d
        sq = jnp.square(dd)
        half = size // 2
        pad_width = [(0, 0)] * dd.ndim
        pad_width[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pad_width)
        acc = sum(jax.lax.slice_in_dim(padded, i, i + dd.shape[1], axis=1)
                  for i in range(size))
        out = dd / jnp.power(k + alpha * acc / size, beta)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return _unary(f, x, name="local_response_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _unary(lambda d: d / jnp.maximum(
        jnp.linalg.norm(d, ord=p, axis=axis, keepdims=True), epsilon), x,
        name="normalize")
