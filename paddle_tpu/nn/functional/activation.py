"""Activation functionals (ref: ``python/paddle/nn/functional/activation.py``).

Every one of these is a single fused VPU expression under XLA — the
reference's per-activation CUDA kernels (phi/kernels/gpu/activation_kernel.cu)
have no equivalent to maintain.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...tensor import Tensor
from ...ops.op_utils import ensure_tensor, unary as _unary, nary
from ...framework import random as _random

__all__ = [
    "relu", "relu_", "relu6", "elu", "elu_", "selu", "celu", "gelu", "silu",
    "swish", "mish", "softplus", "softsign", "softshrink", "hardshrink",
    "tanhshrink", "thresholded_relu", "leaky_relu", "prelu", "rrelu",
    "hardtanh", "hardsigmoid", "hardswish", "sigmoid", "log_sigmoid",
    "tanh", "tanh_", "softmax", "softmax_", "log_softmax", "gumbel_softmax",
    "maxout", "glu", "stanh",
]


def relu(x, name=None):
    return _unary(jax.nn.relu, x, name="relu")


def relu_(x, name=None):
    out = relu(x)
    x._data = out._data
    return out


def relu6(x, name=None):
    return _unary(jax.nn.relu6, x, name="relu6")


def elu(x, alpha=1.0, name=None):
    return _unary(lambda d: jax.nn.elu(d, alpha=alpha), x, name="elu")


elu_ = elu


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _unary(lambda d: scale * jnp.where(
        d > 0, d, alpha * (jnp.exp(d) - 1)), x, name="selu")


def celu(x, alpha=1.0, name=None):
    return _unary(lambda d: jax.nn.celu(d, alpha=alpha), x, name="celu")


def gelu(x, approximate=False, name=None):
    return _unary(lambda d: jax.nn.gelu(d, approximate=approximate), x,
                  name="gelu")


def silu(x, name=None):
    return _unary(jax.nn.silu, x, name="silu")


def swish(x, name=None):
    return silu(x)


def mish(x, name=None):
    return _unary(lambda d: d * jnp.tanh(jax.nn.softplus(d)), x, name="mish")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _unary(lambda d: jnp.where(
        d * beta > threshold, d,
        (1.0 / beta) * jnp.log1p(jnp.exp(beta * d))), x, name="softplus")


def softsign(x, name=None):
    return _unary(jax.nn.soft_sign, x, name="softsign")


def softshrink(x, threshold=0.5, name=None):
    return _unary(lambda d: jnp.where(
        d > threshold, d - threshold,
        jnp.where(d < -threshold, d + threshold, 0.0)), x, name="softshrink")


def hardshrink(x, threshold=0.5, name=None):
    return _unary(lambda d: jnp.where(jnp.abs(d) > threshold, d, 0.0), x,
                  name="hardshrink")


def tanhshrink(x, name=None):
    return _unary(lambda d: d - jnp.tanh(d), x, name="tanhshrink")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return _unary(lambda d: jnp.where(d > threshold, d, value), x,
                  name="thresholded_relu")


def leaky_relu(x, negative_slope=0.01, name=None):
    return _unary(lambda d: jax.nn.leaky_relu(d, negative_slope=negative_slope),
                  x, name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def f(d, w):
        if w.size == 1:
            return jnp.where(d >= 0, d, w.ravel()[0] * d)
        shape = [1] * d.ndim
        ch_axis = 1 if data_format[1] == "C" else d.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(d >= 0, d, w.reshape(shape) * d)
    return nary(f, [x, ensure_tensor(weight)], name="prelu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    x = ensure_tensor(x)
    if training:
        key = _random.next_key()

        def f(d):
            a = jax.random.uniform(key, d.shape, dtype=jnp.float32,
                                   minval=lower, maxval=upper).astype(d.dtype)
            return jnp.where(d >= 0, d, a * d)
        return _unary(f, x, name="rrelu")
    mid = (lower + upper) / 2.0
    return _unary(lambda d: jnp.where(d >= 0, d, mid * d), x, name="rrelu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return _unary(lambda d: jnp.clip(d, min, max), x, name="hardtanh")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return _unary(lambda d: jnp.clip(slope * d + offset, 0.0, 1.0), x,
                  name="hardsigmoid")


def hardswish(x, name=None):
    return _unary(lambda d: d * jnp.clip(d + 3.0, 0.0, 6.0) / 6.0, x,
                  name="hardswish")


def sigmoid(x, name=None):
    return _unary(jax.nn.sigmoid, x, name="sigmoid")


def log_sigmoid(x, name=None):
    return _unary(jax.nn.log_sigmoid, x, name="log_sigmoid")


def tanh(x, name=None):
    return _unary(jnp.tanh, x, name="tanh")


def tanh_(x, name=None):
    out = tanh(x)
    x._data = out._data
    return out


def softmax(x, axis=-1, dtype=None, name=None):
    from ...framework.dtype import to_jax_dtype
    dt = to_jax_dtype(dtype) if dtype is not None else None

    def f(d):
        if dt is not None:
            d = d.astype(dt)
        return jax.nn.softmax(d, axis=axis)
    return _unary(f, x, name="softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis, dtype)
    x._data = out._data
    return out


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...framework.dtype import to_jax_dtype
    dt = to_jax_dtype(dtype) if dtype is not None else None

    def f(d):
        if dt is not None:
            d = d.astype(dt)
        return jax.nn.log_softmax(d, axis=axis)
    return _unary(f, x, name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    x = ensure_tensor(x)
    key = _random.next_key()

    def f(d):
        g = -jnp.log(-jnp.log(
            jax.random.uniform(key, d.shape, dtype=jnp.float32,
                               minval=1e-10, maxval=1.0) + 1e-10)).astype(d.dtype)
        y = jax.nn.softmax((d + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                        inplace=False)
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y
    return _unary(f, x, name="gumbel_softmax")


def maxout(x, groups, axis=1, name=None):
    def f(d):
        ax = axis % d.ndim
        c = d.shape[ax]
        new_shape = d.shape[:ax] + (c // groups, groups) + d.shape[ax + 1:]
        return jnp.max(d.reshape(new_shape), axis=ax + 1)
    return _unary(f, x, name="maxout")


def glu(x, axis=-1, name=None):
    def f(d):
        a, b = jnp.split(d, 2, axis=axis)
        return a * jax.nn.sigmoid(b)
    return _unary(f, x, name="glu")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _unary(lambda d: scale_b * jnp.tanh(scale_a * d), x, name="stanh")
