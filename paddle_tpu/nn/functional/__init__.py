"""``paddle_tpu.nn.functional`` — the functional op surface for nn.

Mirrors ``python/paddle/nn/functional/__init__.py``.
"""
from .activation import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403

from . import activation, conv, pooling, norm, loss, common  # noqa: F401
from . import vision  # noqa: F401
from . import flash_attention  # noqa: F401  (module path, ref parity)
from .flash_attention import flash_attn_unpadded  # noqa: F401
from ..decode import gather_tree  # noqa: F401  (ref: functional/extension.py)

__all__ = (activation.__all__ + conv.__all__ + pooling.__all__ +
           norm.__all__ + loss.__all__ + common.__all__ + vision.__all__ +
           ["flash_attn_unpadded", "gather_tree"])
