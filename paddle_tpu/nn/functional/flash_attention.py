"""``paddle.nn.functional.flash_attention`` (ref:
``python/paddle/nn/functional/flash_attention.py:125 flash_attention``,
``:272 flash_attn_unpadded``) over the Pallas kernel
(``paddle_tpu.ops.pallas_ops``).

The reference's unpadded entry takes packed tokens + ``cu_seqlens``
(CUDA varlen kernels iterate ragged rows). XLA wants static shapes, so
here the packed input is scattered into a padded (B, max_seqlen, H, D)
batch, the kernel masks keys per row via its SMEM length vector, and the
result gathers back to packed layout — all static-shape ops, one fused
program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.op_utils import ensure_tensor, nary
from ...framework import random as _random

__all__ = ["flash_attention", "flash_attn_unpadded"]


def _seed_input(dropout, training):
    if dropout > 0.0 and training:
        bits = jax.random.bits(_random.next_key(), (), jnp.uint32)
        return [ensure_tensor(
            jax.lax.bitcast_convert_type(bits, jnp.float32))]
    return []


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, *, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """(B, S, H, D) tensors; returns (out, softmax) — softmax is None
    unless ``return_softmax``, which falls back to the XLA path (the
    flash kernel never materialises it; same restriction as the
    reference's ``return_softmax`` + fp16 path)."""
    from ...ops.pallas_ops import flash_attention as _fa
    q, k, v = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    if return_softmax:
        from .common import scaled_dot_product_attention
        probs = _softmax_probs(q, k, v, causal)
        out = scaled_dot_product_attention(
            q, k, v, dropout_p=dropout, is_causal=causal, training=training)
        return out, probs
    eff = dropout if training else 0.0
    return _fa(q, k, v, causal=causal, dropout_p=eff), None


def _softmax_probs(q, k, v, causal):
    import numpy as np

    def f(qd, kd, vd):
        qt, kt = jnp.swapaxes(qd, 1, 2), jnp.swapaxes(kd, 1, 2)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(
            qd.shape[-1])
        if causal:
            S, K = logits.shape[-2], logits.shape[-1]
            logits = jnp.where(jnp.tril(jnp.ones((S, K), bool)), logits,
                               -jnp.inf)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    return nary(f, [q, k, v], name="flash_attention_softmax")


def _validate_cu(cu, total, what):
    import numpy as np
    c = np.asarray(cu)
    if c[0] != 0 or (np.diff(c) < 0).any() or c[-1] != total:
        raise ValueError(
            f"{what} must be nondecreasing, start at 0 and end at the "
            f"packed token count {total}; got {c.tolist()[:8]}...")


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Packed ragged varlen attention: ``query`` is (total_q, H, D);
    sequence i occupies rows ``cu_seqlens_q[i]:cu_seqlens_q[i+1]``.

    Runs the genuinely PACKED Pallas kernel (``ops.pallas_ops.mha_packed``):
    sequences are block-aligned in a packed buffer and off-band tiles are
    skipped, so compute is O(sum len_i^2) — no pad-to-max scatter.
    Cross-attention lengths (``cu_seqlens_q != cu_seqlens_k``) are
    supported; ``causal`` uses the flash-attn bottom-right alignment.

    cu_seqlens are VALIDATED eagerly when concrete (raising, not
    NaN-poisoning). Under a jit trace they are tracers and cannot be
    checked for free; set the ``check_varlen`` flag to validate inside
    the traced program via a host callback (debug mode).
    """
    from ...ops.pallas_ops import mha_packed
    from ...framework import flags as _flags
    q = ensure_tensor(query)
    k, v = ensure_tensor(key), ensure_tensor(value)
    cu_q = jnp.asarray(ensure_tensor(cu_seqlens_q)._data, jnp.int32)
    cu_k = jnp.asarray(ensure_tensor(cu_seqlens_k)._data, jnp.int32)
    if not isinstance(cu_q, jax.core.Tracer):
        _validate_cu(cu_q, q.shape[0], "cu_seqlens_q")
    if not isinstance(cu_k, jax.core.Tracer):
        _validate_cu(cu_k, k.shape[0], "cu_seqlens_k")
    eff = dropout if training else 0.0
    seeds = _seed_input(eff, True)
    check = bool(_flags.flag("check_varlen"))

    def f(qd, kd, vd, cu, cuk, *rest):
        if check:
            def _cb(c, ck):
                _validate_cu(c, qd.shape[0], "cu_seqlens_q")
                _validate_cu(ck, kd.shape[0], "cu_seqlens_k")

            # debug.callback is effectful — a pure_callback whose result
            # is unused would be dead-code-eliminated under jit
            jax.debug.callback(_cb, cu, cuk)
        return mha_packed(qd, kd, vd, cu, cuk, causal=causal,
                          sm_scale=scale, dropout_p=eff,
                          seed=rest[0] if rest else None)

    out = nary(f, [q, k, v, ensure_tensor(cu_q), ensure_tensor(cu_k)]
               + seeds, name="flash_attn_unpadded")
    return out, None
