"""``paddle.nn.functional.flash_attention`` (ref:
``python/paddle/nn/functional/flash_attention.py:125 flash_attention``,
``:272 flash_attn_unpadded``) over the Pallas kernel
(``paddle_tpu.ops.pallas_ops``).

The reference's unpadded entry takes packed tokens + ``cu_seqlens``
(CUDA varlen kernels iterate ragged rows). XLA wants static shapes, so
here the packed input is scattered into a padded (B, max_seqlen, H, D)
batch, the kernel masks keys per row via its SMEM length vector, and the
result gathers back to packed layout — all static-shape ops, one fused
program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.op_utils import ensure_tensor, nary
from ...framework import random as _random

__all__ = ["flash_attention", "flash_attn_unpadded"]


def _seed_input(dropout, training):
    if dropout > 0.0 and training:
        bits = jax.random.bits(_random.next_key(), (), jnp.uint32)
        return [ensure_tensor(
            jax.lax.bitcast_convert_type(bits, jnp.float32))]
    return []


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, *, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """(B, S, H, D) tensors; returns (out, softmax) — softmax is None
    unless ``return_softmax``, which falls back to the XLA path (the
    flash kernel never materialises it; same restriction as the
    reference's ``return_softmax`` + fp16 path)."""
    from ...ops.pallas_ops import flash_attention as _fa
    q, k, v = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    if return_softmax:
        from .common import scaled_dot_product_attention
        probs = _softmax_probs(q, k, v, causal)
        out = scaled_dot_product_attention(
            q, k, v, dropout_p=dropout, is_causal=causal, training=training)
        return out, probs
    eff = dropout if training else 0.0
    from ...ops.pallas_ops import _interpret_default
    from .common import _on_tpu, _flash_usable
    if not _interpret_default() and _on_tpu() and not _flash_usable():
        # kernel cannot lower on this chip: keep the caller's jitted
        # step alive via the XLA path (sdpa re-checks the same canary,
        # so it cannot bounce back here)
        from .common import scaled_dot_product_attention
        out = scaled_dot_product_attention(
            q, k, v, dropout_p=eff, is_causal=causal, training=training)
        return out, None
    return _fa(q, k, v, causal=causal, dropout_p=eff), None


def _softmax_probs(q, k, v, causal):
    import numpy as np

    def f(qd, kd, vd):
        qt, kt = jnp.swapaxes(qd, 1, 2), jnp.swapaxes(kd, 1, 2)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(
            qd.shape[-1])
        if causal:
            S, K = logits.shape[-2], logits.shape[-1]
            logits = jnp.where(jnp.tril(jnp.ones((S, K), bool)), logits,
                               -jnp.inf)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    return nary(f, [q, k, v], name="flash_attention_softmax")


def _packed_usable():
    """One-time eager canary of the packed varlen kernel (shared
    ``_kernel_canary`` mechanism, ``common.py``): Pallas kernels that
    trace fine can still fail at LOWERING time on real TPU, and under
    ``jax.jit`` that failure escapes call-site try/excepts. On failure
    the unpadded entry drops to the exact padded-XLA fallback instead
    of killing the caller's compiled step.

    The probe must be REPRESENTATIVE of production lowering configs:
    >512 packed tokens so the full 512-block tiles lower (a small probe
    would cap ``bq`` below the production tile and miss VMEM-limit
    failures), plus fwd+dropout and both backward kernels in bf16, and
    a small f32 variant for dtype-specific tiling rules."""
    from .common import _kernel_canary

    def probe():
        from ...ops.pallas_ops import mha_packed
        x = jnp.zeros((640, 4, 64), jnp.bfloat16)  # > 512 => 512-blocks
        cu = jnp.asarray([0, 128, 640], jnp.int32)
        out = mha_packed(x, x, x, cu, cu, causal=True, interpret=False)
        seed = jnp.ones((), jnp.float32)
        g = jax.grad(lambda q: mha_packed(
            q, x, x, cu, cu, causal=True, dropout_p=0.1, seed=seed,
            interpret=False).astype(jnp.float32).sum())(x)
        xf = jnp.zeros((96, 2, 64), jnp.float32)
        cuf = jnp.asarray([0, 40, 96], jnp.int32)
        outf = mha_packed(xf, xf, xf, cuf, cuf, causal=False,
                          interpret=False)
        return out, g, outf
    return _kernel_canary("flash_mha_packed", probe)


def _padded_fallback(qd, kd, vd, cu_q, cu_k, max_q, max_k, causal, scale,
                     dropout_p, seed):
    """Exact XLA fallback for the packed kernel: scatter packed rows into
    a (B, max, H, D) batch, run masked attention (same bottom-right
    causal alignment: col <= row + len_k - len_q), gather back. Compute
    is O(B*max^2) — correct but without the packed kernel's off-band
    tile skipping; only used when the kernel cannot lower."""
    total_q, H, D = qd.shape
    total_k = kd.shape[0]
    B = cu_q.shape[0] - 1
    lens_q = cu_q[1:] - cu_q[:-1]
    lens_k = cu_k[1:] - cu_k[:-1]
    iq = jnp.arange(max_q, dtype=jnp.int32)
    ik = jnp.arange(max_k, dtype=jnp.int32)
    valid_q = iq[None, :] < lens_q[:, None]                  # (B, max_q)
    valid_k = ik[None, :] < lens_k[:, None]                  # (B, max_k)
    tok_q = jnp.clip(cu_q[:-1, None] + iq[None, :], 0, max(total_q - 1, 0))
    tok_k = jnp.clip(cu_k[:-1, None] + ik[None, :], 0, max(total_k - 1, 0))
    qb = qd[tok_q] * valid_q[..., None, None]                # (B,max_q,H,D)
    kb = kd[tok_k] * valid_k[..., None, None]
    vb = vd[tok_k] * valid_k[..., None, None]
    logits = jnp.einsum("bqhd,bkhd->bhqk", qb, kb) * scale
    mask = valid_k[:, None, None, :]
    if causal:
        off = (lens_k - lens_q)[:, None, None, None]
        mask = mask & (ik[None, None, None, :]
                       <= iq[None, None, :, None] + off)
    neg = jnp.finfo(jnp.float32).min
    probs = jax.nn.softmax(
        jnp.where(mask, logits.astype(jnp.float32), neg), axis=-1)
    # fully-masked rows (len_q > len_k under causal) produce uniform
    # softmax over garbage; zero them like the kernel does
    probs = jnp.where(mask.any(-1, keepdims=True), probs, 0.0)
    if dropout_p > 0.0 and seed is not None:
        key = jax.random.PRNGKey(
            jax.lax.bitcast_convert_type(seed, jnp.int32))
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    ob = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(qd.dtype), vb)
    tq = jnp.arange(total_q, dtype=jnp.int32)
    s_of = jnp.clip(jnp.searchsorted(cu_q, tq, side="right") - 1, 0, B - 1)
    return ob[s_of, tq - cu_q[s_of]]                         # (total_q,H,D)


def _validate_cu(cu, total, what, max_seqlen=None):
    import numpy as np
    c = np.asarray(cu)
    if c[0] != 0 or (np.diff(c) < 0).any() or c[-1] != total:
        raise ValueError(
            f"{what} must be nondecreasing, start at 0 and end at the "
            f"packed token count {total}; got {c.tolist()[:8]}...")
    # max_seqlen is load-bearing on the padded fallback path (rows past
    # it would be silently dropped + clamp-duplicated on gather-back);
    # an understated value is caller error on either path — reject it.
    if max_seqlen is not None and len(c) > 1:
        longest = int(np.diff(c).max())
        if longest > int(max_seqlen):
            raise ValueError(
                f"max_seqlen for {what} is {int(max_seqlen)} but the "
                f"longest sequence is {longest}")


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Packed ragged varlen attention: ``query`` is (total_q, H, D);
    sequence i occupies rows ``cu_seqlens_q[i]:cu_seqlens_q[i+1]``.

    Runs the genuinely PACKED Pallas kernel (``ops.pallas_ops.mha_packed``):
    sequences are block-aligned in a packed buffer and off-band tiles are
    skipped, so compute is O(sum len_i^2) — no pad-to-max scatter.
    Cross-attention lengths (``cu_seqlens_q != cu_seqlens_k``) are
    supported; ``causal`` uses the flash-attn bottom-right alignment.

    cu_seqlens are VALIDATED eagerly when concrete (raising, not
    NaN-poisoning). Under a jit trace they are tracers and cannot be
    checked for free; set the ``check_varlen`` flag to validate inside
    the traced program via a host callback (debug mode).
    """
    from ...ops.pallas_ops import mha_packed, _interpret_default
    from ...framework import flags as _flags
    from .common import _on_tpu
    q = ensure_tensor(query)
    k, v = ensure_tensor(key), ensure_tensor(value)
    cu_q = jnp.asarray(ensure_tensor(cu_seqlens_q)._data, jnp.int32)
    cu_k = jnp.asarray(ensure_tensor(cu_seqlens_k)._data, jnp.int32)
    if not isinstance(cu_q, jax.core.Tracer):
        _validate_cu(cu_q, q.shape[0], "cu_seqlens_q", max_seqlen_q)
    if not isinstance(cu_k, jax.core.Tracer):
        _validate_cu(cu_k, k.shape[0], "cu_seqlens_k", max_seqlen_k)
    eff = dropout if training else 0.0
    seeds = _seed_input(eff, True)
    check = bool(_flags.flag("check_varlen"))
    # interpret mode (CPU) is always exact; on real TPU the kernel is
    # used only after its eager canary proves it lowers — otherwise the
    # exact padded-XLA fallback keeps the caller's jitted step alive
    use_kernel = _interpret_default() or (_on_tpu() and _packed_usable())

    def f(qd, kd, vd, cu, cuk, *rest):
        if check:
            def _cb(c, ck):
                _validate_cu(c, qd.shape[0], "cu_seqlens_q", max_seqlen_q)
                _validate_cu(ck, kd.shape[0], "cu_seqlens_k", max_seqlen_k)

            # debug.callback is effectful — a pure_callback whose result
            # is unused would be dead-code-eliminated under jit
            jax.debug.callback(_cb, cu, cuk)
        if not use_kernel:
            return _padded_fallback(qd, kd, vd, cu, cuk,
                                    int(max_seqlen_q), int(max_seqlen_k),
                                    causal, scale, eff,
                                    rest[0] if rest else None)
        return mha_packed(qd, kd, vd, cu, cuk, causal=causal,
                          sm_scale=scale, dropout_p=eff,
                          seed=rest[0] if rest else None)

    out = nary(f, [q, k, v, ensure_tensor(cu_q), ensure_tensor(cu_k)]
               + seeds, name="flash_attn_unpadded")
    return out, None
