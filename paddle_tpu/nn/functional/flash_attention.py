"""``paddle.nn.functional.flash_attention`` (ref:
``python/paddle/nn/functional/flash_attention.py:125 flash_attention``,
``:272 flash_attn_unpadded``) over the Pallas kernel
(``paddle_tpu.ops.pallas_ops``).

The reference's unpadded entry takes packed tokens + ``cu_seqlens``
(CUDA varlen kernels iterate ragged rows). XLA wants static shapes, so
here the packed input is scattered into a padded (B, max_seqlen, H, D)
batch, the kernel masks keys per row via its SMEM length vector, and the
result gathers back to packed layout — all static-shape ops, one fused
program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.op_utils import ensure_tensor, nary
from ...framework import random as _random

__all__ = ["flash_attention", "flash_attn_unpadded"]


def _seed_input(dropout, training):
    if dropout > 0.0 and training:
        bits = jax.random.bits(_random.next_key(), (), jnp.uint32)
        return [ensure_tensor(
            jax.lax.bitcast_convert_type(bits, jnp.float32))]
    return []


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, *, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """(B, S, H, D) tensors; returns (out, softmax) — softmax is None
    unless ``return_softmax``, which falls back to the XLA path (the
    flash kernel never materialises it; same restriction as the
    reference's ``return_softmax`` + fp16 path)."""
    from ...ops.pallas_ops import flash_attention as _fa
    q, k, v = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    if return_softmax:
        from .common import scaled_dot_product_attention
        probs = _softmax_probs(q, k, v, causal)
        out = scaled_dot_product_attention(
            q, k, v, dropout_p=dropout, is_causal=causal, training=training)
        return out, probs
    eff = dropout if training else 0.0
    return _fa(q, k, v, causal=causal, dropout_p=eff), None


def _softmax_probs(q, k, v, causal):
    import numpy as np

    def f(qd, kd, vd):
        qt, kt = jnp.swapaxes(qd, 1, 2), jnp.swapaxes(kd, 1, 2)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(
            qd.shape[-1])
        if causal:
            S, K = logits.shape[-2], logits.shape[-1]
            logits = jnp.where(jnp.tril(jnp.ones((S, K), bool)), logits,
                               -jnp.inf)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    return nary(f, [q, k, v], name="flash_attention_softmax")


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Packed varlen attention: ``query`` is (total_q, H, D); sequence i
    occupies rows ``cu_seqlens_q[i]:cu_seqlens_q[i+1]``. Self-attention
    lengths only (cu_seqlens_q == cu_seqlens_k), like the reference's
    main use (BERT-style padded batches)."""
    from ...ops.pallas_ops import mha
    import numpy as np
    q = ensure_tensor(query)
    k, v = ensure_tensor(key), ensure_tensor(value)
    cu_q = jnp.asarray(ensure_tensor(cu_seqlens_q)._data, jnp.int32)
    cu_k = jnp.asarray(ensure_tensor(cu_seqlens_k)._data, jnp.int32)
    # validate only when concrete: under a jit/to_static trace the cu
    # arrays are tracers (and eager validation costs one host transfer,
    # which is what a data-dependent check is)
    if not isinstance(cu_q, jax.core.Tracer) and \
            not isinstance(cu_k, jax.core.Tracer):
        cq = np.asarray(cu_q)
        if not np.array_equal(cq, np.asarray(cu_k)):
            raise NotImplementedError(
                "flash_attn_unpadded currently supports self-attention "
                "lengths only (cu_seqlens_q == cu_seqlens_k); "
                "cross-attention varlen is not implemented")
        if (np.diff(cq) > int(max_seqlen_q)).any():
            raise ValueError(
                f"a sequence exceeds max_seqlen_q={max_seqlen_q}; longer "
                f"sequences would be silently truncated")
    max_q = int(max_seqlen_q)
    eff = dropout if training else 0.0
    seeds = _seed_input(eff, True)

    def f(qd, kd, vd, cu, cuk, *rest):
        bsz = cu.shape[0] - 1
        h, d = qd.shape[1], qd.shape[2]
        lens = cu[1:] - cu[:-1]
        # traced guard: the eager-only validation above is skipped for
        # tracers, so poison the output with NaN (visible, not silent)
        # if cu_q != cu_k or a sequence overflows max_seqlen at runtime
        ok = jnp.logical_and((cu == cuk).all(), (lens <= max_q).all())
        # scatter packed rows -> (B, max_q) padded positions
        pos = jnp.arange(max_q, dtype=jnp.int32)
        idx = cu[:-1, None] + pos[None, :]                  # (B, max_q)
        idx = jnp.minimum(idx, qd.shape[0] - 1)
        valid = pos[None, :] < lens[:, None]

        def pad(x):
            g = x[idx.reshape(-1)].reshape(bsz, max_q, h, d)
            return jnp.where(valid[:, :, None, None], g, 0.0)

        qp, kp, vp = pad(qd), pad(kd), pad(vd)
        out = mha(jnp.swapaxes(qp, 1, 2), jnp.swapaxes(kp, 1, 2),
                  jnp.swapaxes(vp, 1, 2), causal=causal, sm_scale=scale,
                  dropout_p=eff, seed=rest[0] if rest else None,
                  seq_lens=lens)
        out = jnp.swapaxes(out, 1, 2)                        # (B,max_q,H,D)
        # gather padded -> packed: row t belongs to seq searchsorted(t)
        tok = jnp.arange(qd.shape[0], dtype=jnp.int32)
        seq_of = jnp.searchsorted(cu, tok, side="right") - 1
        off = tok - cu[seq_of]
        packed = out[seq_of, off]
        return jnp.where(ok, packed, jnp.nan)

    out = nary(f, [q, k, v, ensure_tensor(cu_q), ensure_tensor(cu_k)]
               + seeds, name="flash_attn_unpadded")
    return out, None
