"""Spatial sampling ops (ref: ``python/paddle/nn/functional/vision.py``
``affine_grid`` / ``grid_sample`` → ``phi/kernels/.../grid_sample_kernel``).

TPU-native: both are pure gather/arithmetic programs — the bilinear
sample is four gathers + a lerp that XLA fuses, jit- and grad-friendly
(no custom CUDA sampler kernel needed).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.op_utils import ensure_tensor, nary

__all__ = ["affine_grid", "grid_sample"]


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta ``[N, 2, 3]`` affine matrices → sampling grid
    ``[N, H, W, 2]`` of normalized (x, y) coords in [-1, 1]."""
    if hasattr(out_shape, "_data"):
        out_shape = [int(v) for v in out_shape._data]
    N, C, H, W = [int(v) for v in out_shape]

    def f(th):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1.0
            ys = (jnp.arange(H) * 2 + 1) / H - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
        # [N,2,3] @ [H*W,3]^T -> [N,H,W,2]
        out = jnp.einsum("nij,hwj->nhwi", th.astype(jnp.float32), base)
        return out.astype(th.dtype)

    return nary(f, [ensure_tensor(theta)], name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample ``x [N, C, H, W]`` at ``grid [N, Hg, Wg, 2]`` normalized
    (x, y) locations. Modes: bilinear | nearest; padding: zeros | border
    | reflection (reference semantics)."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"mode must be bilinear|nearest, got {mode}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"bad padding_mode {padding_mode}")

    def f(xd, g):
        N, C, H, W = xd.shape
        gf = g.astype(jnp.float32)
        if align_corners:
            ix = (gf[..., 0] + 1) / 2 * (W - 1)
            iy = (gf[..., 1] + 1) / 2 * (H - 1)
        else:
            ix = ((gf[..., 0] + 1) * W - 1) / 2
            iy = ((gf[..., 1] + 1) * H - 1) / 2

        def reflect(v, lo, hi):
            # reflect into [lo, hi] (reference GridSampler reflection)
            if hi <= lo:
                return jnp.zeros_like(v)
            rng_ = hi - lo
            v = jnp.abs(v - lo) % (2 * rng_)
            return lo + jnp.where(v > rng_, 2 * rng_ - v, v)

        if padding_mode == "reflection":
            if align_corners:
                ix = reflect(ix, 0.0, W - 1.0)
                iy = reflect(iy, 0.0, H - 1.0)
            else:
                ix = jnp.clip(reflect(ix, -0.5, W - 0.5), 0, W - 1)
                iy = jnp.clip(reflect(iy, -0.5, H - 0.5), 0, H - 1)
        def ok(yi, xi):
            if padding_mode != "zeros":
                return jnp.ones_like(yi)
            return ((yi >= 0) & (yi <= H - 1) & (xi >= 0)
                    & (xi <= W - 1)).astype(jnp.float32)

        def fetch(yi, xi, valid):
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            flat = xd.reshape(N, C, H * W)
            idx = (yc * W + xc).reshape(N, 1, -1)
            got = jnp.take_along_axis(
                flat, jnp.broadcast_to(idx, (N, C, idx.shape[-1])), axis=2)
            got = got.reshape(N, C, *yi.shape[1:])
            if padding_mode == "zeros":
                got = got * valid[:, None].astype(got.dtype)
            return got

        if mode == "nearest":
            yn, xn = jnp.round(iy), jnp.round(ix)
            return fetch(yn, xn, ok(yn, xn))

        x0, y0 = jnp.floor(ix), jnp.floor(iy)
        wx, wy = ix - x0, iy - y0

        v00 = fetch(y0, x0, ok(y0, x0))
        v01 = fetch(y0, x0 + 1, ok(y0, x0 + 1))
        v10 = fetch(y0 + 1, x0, ok(y0 + 1, x0))
        v11 = fetch(y0 + 1, x0 + 1, ok(y0 + 1, x0 + 1))
        wx = wx[:, None]
        wy = wy[:, None]
        out = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
               + v10 * wy * (1 - wx) + v11 * wy * wx)
        return out.astype(xd.dtype)

    return nary(f, [ensure_tensor(x), ensure_tensor(grid)],
                name="grid_sample")
