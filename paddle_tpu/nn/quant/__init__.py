"""``paddle.nn.quant`` (ref: ``python/paddle/nn/quant/``): layer-side
quantization helpers. The working PTQ/QAT machinery lives in
:mod:`paddle_tpu.quantization`; this module carries the layer-facing
``Stub`` placeholder (the only name the reference exports here)."""
from __future__ import annotations

from ..layer.layers import Layer

__all__ = ["Stub"]


class Stub(Layer):
    """Observer placeholder (ref ``nn/quant/stub.py:20``): inserted in a
    forward where a functional API needs quantization; the QAT/PTQ pass
    replaces it with the configured observer. Identity until then."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, input):
        return input
