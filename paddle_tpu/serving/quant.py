"""PTQ calibration harness + the quantized served-model format.

The int8 serving pipeline in three moves:

 1. **Calibrate** — :func:`calibrate` replays a prefill/decode trace
    *eagerly* through the same :mod:`.model` step functions the engine
    compiles, with the steps' ``tap`` hook feeding the existing
    :mod:`paddle_tpu.quantization` observers: a
    :class:`~paddle_tpu.quantization.observers.PerChannelAbsmaxObserver`
    per weight matrix and an
    :class:`~paddle_tpu.quantization.observers.AbsmaxObserver` per
    activation site.  Calibration never touches an engine, so it can't
    trip an armed serve compile sentinel.
 2. **Quantize** — :func:`quantize_params` rewrites the flat weight
    dict: each projection/MLP matrix ``name`` becomes ``name::q``
    (int8) + ``name::scale`` (f32 per-out-channel); per-tensor
    activation scales ride along as ``act::<site>::scale`` leaves so
    a future a8 path needs no re-calibration.  The model's matmul
    helper dispatches on the ``::q`` key at trace time, so one set of
    step functions serves every precision.
 3. **Save/load** — :func:`save_quantized_model` writes a served-model
    dir whose ``serve_config.json`` carries a ``precision`` block and
    whose checkpoint holds the quantized tree; ``load_engine`` builds
    its restore template from :func:`quantized_template` so treedef
    validation still bites.

Quality is tracked as **max-logit-divergence** vs the fp32 oracle
(:func:`logit_divergence`) on the toy model; the tolerance is pinned by
``tests/test_serving_quant.py`` and re-measured by
``bench_serve.py --precision int8``.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..ops.quant_kernels import quantize_weight
from ..quantization.observers import AbsmaxObserver, PerChannelAbsmaxObserver
from .model import (ModelSpec, QUANT_WEIGHT_NAMES, decode_step, init_params,
                    prefill_step)

__all__ = ["calibrate", "quantize_params", "is_quantized_params",
           "quantized_template", "save_quantized_model",
           "logit_divergence", "default_calibration_prompts",
           "PRECISION_SCHEME"]

PRECISION_SCHEME = {
    "mode": "int8",
    "weights": "per-channel-absmax (out-channel), symmetric, no zero-point",
    "activations": "per-tensor-absmax, recorded for a8 follow-on",
    "kv_cache": "int8 per-(token,head) dynamic scales in shadow scale pages",
}


def default_calibration_prompts(spec: ModelSpec, n: int = 4,
                                seed: int = 0) -> List[List[int]]:
    """Deterministic toy calibration set (the bench/test corpus)."""
    rng = np.random.RandomState(seed)
    return [rng.randint(1, spec.vocab_size,
                        size=int(rng.randint(3, 13))).tolist()
            for _ in range(n)]


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------
class _TapObservers:
    """The ``tap(site, activation)`` hook: one per-tensor absmax
    observer per activation site (matmul inputs + the head input)."""

    def __init__(self):
        self.observers: Dict[str, AbsmaxObserver] = {}
        self.samples = 0

    def __call__(self, site: str, x) -> None:
        obs = self.observers.get(site)
        if obs is None:
            obs = self.observers[site] = AbsmaxObserver()
        # calibration is eager host-side replay by design — the
        # observers are numpy machinery and never run under the engine
        obs.observe(np.asarray(x, np.float32))  # tpu-lint: disable=TPU003
        self.samples += 1

    def scales(self) -> Dict[str, float]:
        return {site: float(o.scales())
                for site, o in sorted(self.observers.items())}


def calibrate(spec: ModelSpec, params, prompts: Sequence[Sequence[int]],
              *, max_new: int = 4, page_size: int = 8) -> Dict[str, Any]:
    """Run the PTQ observers over a captured prefill/decode trace.

    Replays each prompt through :func:`prefill_step` and ``max_new``
    :func:`decode_step` calls eagerly (fp32, throwaway KV pools sized
    per prompt), tapping every quantizable matmul input.  Also folds
    each weight matrix through a
    :class:`PerChannelAbsmaxObserver` so the weight scales come from
    the same observer machinery QAT uses.

    Returns ``{"act_scales", "weight_scales", "samples", "prompts"}``.
    """
    from .engine import aot_build_phase
    tap = _TapObservers()
    weight_obs: Dict[str, PerChannelAbsmaxObserver] = {}
    for name in QUANT_WEIGHT_NAMES(spec):
        obs = PerChannelAbsmaxObserver(quant_axis_=1)
        obs.observe(np.asarray(params[name], np.float32))
        weight_obs[name] = obs

    # eager replay compiles per prompt shape: a sanctioned build phase,
    # so calibrating next to a LIVE armed engine (blue/green requantize)
    # never books pt_serve_unexpected_compiles_total on it
    with aot_build_phase():
        for prompt in prompts:
            total = len(prompt) + max_new
            pages = 1 + -(-total // page_size)
            shape = (spec.layers, pages * page_size, spec.heads,
                     spec.head_dim)
            k_flat = jnp.zeros(shape, jnp.float32)
            v_flat = jnp.zeros(shape, jnp.float32)
            table = np.arange(1, pages, dtype=np.int32)
            padded = np.zeros((len(prompt),), np.int32)
            padded[:] = np.asarray(prompt, np.int32)
            k_flat, v_flat, nxt, _ = prefill_step(
                spec, params, k_flat, v_flat, padded,
                np.int32(len(prompt)), table, page_size=page_size, tap=tap)
            tok = np.asarray(nxt, np.int32).reshape(1)
            for j in range(max_new):
                pos = np.asarray([len(prompt) + j], np.int32)
                k_flat, v_flat, tok, _ = decode_step(
                    spec, params, k_flat, v_flat, tok, pos, table[None, :],
                    page_size=page_size, tap=tap)
                tok = np.asarray(tok, np.int32)

    return {
        "act_scales": tap.scales(),
        "weight_scales": {n: np.asarray(o.scales(), np.float32)
                          for n, o in sorted(weight_obs.items())},
        "samples": tap.samples,
        "prompts": len(list(prompts)),
    }


# ---------------------------------------------------------------------------
# weight quantization
# ---------------------------------------------------------------------------
def is_quantized_params(params) -> bool:
    return any(str(k).endswith("::q") for k in params)


def quantize_params(params, spec: ModelSpec,
                    act_scales: Optional[Dict[str, float]] = None
                    ) -> Dict[str, Any]:
    """Rewrite a flat fp32 weight dict into the int8 serve layout.

    Each quantizable matrix ``name`` is replaced (in place in the key
    order) by ``name::q`` + ``name::scale``; everything else passes
    through.  ``act_scales`` (from :func:`calibrate`) are appended as
    ``act::<site>::scale`` scalar leaves.  Deterministic — same weights
    always produce the same bytes, which is what lets an engine given
    fp32 weights under ``precision=int8`` quantize inline and still
    match a saved quantized dir bit for bit.
    """
    if is_quantized_params(params):
        return dict(params)
    targets = set(QUANT_WEIGHT_NAMES(spec))
    out: Dict[str, Any] = {}
    for name, w in params.items():
        if name in targets:
            q, s = quantize_weight(w, axis=1)
            out[name + "::q"] = q
            out[name + "::scale"] = s
        else:
            out[name] = w
    for site, scale in sorted((act_scales or {}).items()):
        out[f"act::{site}::scale"] = jnp.asarray([scale], jnp.float32)
    return out


def quantized_template(spec: ModelSpec,
                       act_sites: Optional[Sequence[str]] = None
                       ) -> Dict[str, Any]:
    """Shape/treedef template for restoring a quantized checkpoint —
    the ``load_engine`` validation hook.  ``act_sites`` lists the
    calibration sites recorded in the dir's precision block."""
    base = quantize_params(init_params(spec, seed=0), spec)
    for site in act_sites or ():
        base[f"act::{site}::scale"] = jnp.zeros((1,), jnp.float32)
    return base


# ---------------------------------------------------------------------------
# quantized served-model dirs
# ---------------------------------------------------------------------------
def save_quantized_model(path: str, spec: ModelSpec, params,
                         config=None, prompts=None, *, max_new: int = 4,
                         step: int = 0) -> str:
    """Calibrate + quantize + write a self-describing quantized
    served-model dir.

    ``serve_config.json`` grows a ``precision`` block (scheme, the
    calibration corpus fingerprint, per-tensor activation scales) and
    its ``serve.precision`` is pinned to ``int8``; the checkpoint holds
    the quantized tree :func:`quantized_template` round-trips.
    """
    from ..distributed.checkpoint_manager import CheckpointManager
    from .engine import SERVE_CONFIG_NAME, ServeConfig, aot_build_phase
    os.makedirs(path, exist_ok=True)
    cfg = (config or ServeConfig.from_env()).replace(precision="int8")
    if prompts is None:
        prompts = default_calibration_prompts(spec)
    with aot_build_phase():
        # quantize_params / checkpoint save run jnp ops eagerly — a
        # sanctioned build phase, like the calibration replay above
        cal = calibrate(spec, params, prompts, max_new=max_new,
                        page_size=cfg.page_size)
        qparams = quantize_params(params, spec,
                                  act_scales=cal["act_scales"])
    meta = {
        "model": spec.to_dict(),
        "serve": cfg.to_dict(),
        "precision": {
            **PRECISION_SCHEME,
            "act_scales": cal["act_scales"],
            "calibration": {"prompts": cal["prompts"],
                            "samples": cal["samples"],
                            "max_new": max_new},
            "quantized_weights": QUANT_WEIGHT_NAMES(spec),
        },
    }
    with open(os.path.join(path, SERVE_CONFIG_NAME), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    with aot_build_phase():
        mgr = CheckpointManager(os.path.join(path, "weights"))
        mgr.save(step, dict(qparams), block=True)
    return path


# ---------------------------------------------------------------------------
# quality: max-logit-divergence vs the fp32 oracle
# ---------------------------------------------------------------------------
def logit_divergence(spec: ModelSpec, params, prompts=None, *,
                     max_new: int = 4, page_size: int = 8,
                     qparams=None) -> float:
    """Max absolute logit gap between the int8 serve path (quantized
    weights + int8 KV pool) and the fp32 oracle, over prefill + decode
    of ``prompts`` — the quality contract the test tolerance pins.

    Greedy token choices FOLLOW the fp32 path so both runs score the
    same token sequence (a divergence metric, not an accuracy proxy).
    Runs eagerly inside a sanctioned build phase, so it is safe next to
    a live armed engine.
    """
    from .engine import aot_build_phase
    if prompts is None:
        prompts = default_calibration_prompts(spec)
    if qparams is None:
        qparams = quantize_params(params, spec)
    worst = 0.0
    with aot_build_phase():
        for prompt in prompts:
            total = len(prompt) + max_new
            pages = 1 + -(-total // page_size)
            shape = (spec.layers, pages * page_size, spec.heads,
                     spec.head_dim)
            sshape = shape[:-1]
            kf = jnp.zeros(shape, jnp.float32)
            vf = jnp.zeros(shape, jnp.float32)
            kq = jnp.zeros(shape, jnp.int8)
            vq = jnp.zeros(shape, jnp.int8)
            ks = jnp.zeros(sshape, jnp.float32)
            vs = jnp.zeros(sshape, jnp.float32)
            table = np.arange(1, pages, dtype=np.int32)
            padded = np.asarray(prompt, np.int32)
            n = np.int32(len(prompt))
            kf, vf, tok, lg_f = prefill_step(
                spec, params, kf, vf, padded, n, table, page_size=page_size)
            kq, vq, ks, vs, _, lg_q = prefill_step(
                spec, qparams, kq, vq, padded, n, table,
                page_size=page_size, k_scale=ks, v_scale=vs)
            worst = max(worst, float(jnp.max(jnp.abs(lg_q - lg_f))))
            tok = np.asarray(tok, np.int32).reshape(1)
            for j in range(max_new):
                pos = np.asarray([len(prompt) + j], np.int32)
                kf, vf, nxt, lg_f = decode_step(
                    spec, params, kf, vf, tok, pos, table[None, :],
                    page_size=page_size)
                kq, vq, ks, vs, _, lg_q = decode_step(
                    spec, qparams, kq, vq, tok, pos, table[None, :],
                    page_size=page_size, k_scale=ks, v_scale=vs)
                worst = max(worst, float(jnp.max(jnp.abs(lg_q - lg_f))))
                tok = np.asarray(nxt, np.int32)
    return worst
