"""``python -m paddle_tpu.serving`` — run the HTTP serving engine as a
supervised process with the full resilience lifecycle.

Builds the engine (from a served-model dir, or a toy ``--spec`` JSON
for drills/smoke), binds the stdlib front end, publishes the bound
endpoint to ``--port-file`` (atomic write — the supervisor/drill reads
``host:port`` once the file lands), installs the SIGTERM graceful-drain
handler (exit 143), and serves until told to stop.

This is the process the serve chaos drill SIGKILLs, deadline-storms,
and SIGTERMs — a real engine with a real AOT ladder, not a mock.
Resilience knobs ride the standard env surface: ``PT_SERVE_DEADLINE_MS``
(server-default deadline), ``PT_SERVE_DRAIN_S`` (drain budget),
``PT_SERVE_WATCHDOG`` (hang sentinel: ``1`` degrades health, ``exit``
fast-exits for supervisor restart).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import threading


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving",
        description="serve a model over HTTP with drain/deadline/"
                    "watchdog resilience")
    ap.add_argument("--model", default=None,
                    help="served-model dir (save_served_model output)")
    ap.add_argument("--spec", default=None,
                    help="toy ModelSpec JSON (drills/smoke) — mutually "
                         "exclusive with --model")
    ap.add_argument("--seed", type=int, default=0,
                    help="init seed for --spec engines")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral (published via --port-file)")
    ap.add_argument("--port-file", default=None,
                    help="publish host:port here once bound")
    ap.add_argument("--request-timeout", type=float, default=120.0)
    ap.add_argument("--drain-budget", type=float, default=None,
                    help="SIGTERM drain budget; default "
                         "ServeConfig.drain_s / PT_SERVE_DRAIN_S")
    ap.add_argument("--no-telemetry", action="store_true",
                    help="skip enabling metrics/compile-watch")
    return ap.parse_args(argv)


def _publish_endpoint(path, host, port):
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="ascii") as f:
        f.write(f"{host}:{port}")
    os.replace(tmp, path)


def main(argv=None):
    args = parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    if bool(args.model) == bool(args.spec):
        print("exactly one of --model / --spec is required",
              file=sys.stderr)
        return 2

    if not args.no_telemetry:
        from ..observability.telemetry import get_telemetry
        get_telemetry().enable()

    from . import (ModelSpec, ServeConfig, ServingEngine, init_params,
                   load_engine)
    from .http import ServeHTTPServer, install_drain_handler

    if args.model:
        engine = load_engine(args.model)
    else:
        spec = ModelSpec.from_dict(json.loads(args.spec))
        engine = ServingEngine(spec, init_params(spec, args.seed),
                               ServeConfig.from_env())

    server = ServeHTTPServer(engine, host=args.host, port=args.port,
                             request_timeout=args.request_timeout).start()
    install_drain_handler(server, budget_s=args.drain_budget)
    if args.port_file:
        _publish_endpoint(args.port_file, server.host, server.port)
    logging.getLogger("paddle_tpu.serving").info(
        "serving pid=%d on http://%s:%d", os.getpid(), server.host,
        server.port)

    # hold until a signal takes us down: SIGTERM drains (exit 143),
    # SIGKILL is the chaos case the relaunch path must absorb
    hold = threading.Event()
    try:
        while not hold.wait(1.0):
            pass
    except KeyboardInterrupt:
        server.stop()
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
