"""Serve-side decoder model: pure functions over a paged KV-cache.

The engine AOT-compiles two program families over these functions
(:mod:`.engine`):

 - ``prefill`` — one sequence, one padded seq-bucket: run the prompt
   through the stack with a causal+length mask, scatter the prompt's
   K/V into the sequence's pages, emit the first generated token.
 - ``decode`` — one padded batch-bucket: one new token per row,
   append its K/V at the row's write slot, attend over the row's page
   list via :func:`paddle_tpu.ops.paged_attention.paged_attention`.

Everything is shaped by :class:`ModelSpec`, a plain dataclass that
round-trips through ``serve_config.json`` so a served model dir is
self-describing (the `paddle/fluid/inference` saved-model contract).

Determinism contract (load-bearing for continuous batching): decode
math is strictly row-independent — same weights + same per-row state
produce bit-identical logits regardless of batch composition or
physical page placement.  The one XLA exception is batch=1, which hits
a gemv path with a different reduction order; the engine therefore
clamps its decode bucket ladder to >= 2 rows (see
``ServeConfig._normalize``), and tests pin the bit-identity claim.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..ops.paged_attention import paged_attention, paged_attention_int8
from ..ops.quant_kernels import quantize_kv, w8a16_matmul

__all__ = ["ModelSpec", "init_params", "prefill_step", "decode_step",
           "QUANT_WEIGHT_NAMES"]

_LN_EPS = 1e-5


def QUANT_WEIGHT_NAMES(spec: "ModelSpec"):
    """The weight matrices the int8 serve path quantizes: every
    projection/MLP matmul.  Embedding, positional table, norms and
    biases stay f32 (tiny, and the tied logits matmul wants the full-
    precision embedding)."""
    names = []
    for i in range(spec.layers):
        names += [f"h{i}.attn.wq", f"h{i}.attn.wk", f"h{i}.attn.wv",
                  f"h{i}.attn.wo", f"h{i}.mlp.w1", f"h{i}.mlp.w2"]
    return names


def _matmul(params, name, x, tap=None):
    """Precision-dispatching matmul: a weight present as ``name::q`` +
    ``name::scale`` (the :mod:`.quant` checkpoint layout) runs through
    the w8a16 kernel; otherwise the plain dense path.  ``tap`` is the
    calibration hook — called with the matmul's input activation so the
    PTQ observers see the same tensors the serve program computes."""
    if tap is not None:
        tap(name, x)
    qk = name + "::q"
    if qk in params:
        return w8a16_matmul(x, params[qk], params[name + "::scale"])
    return x @ params[name]


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Architecture hyperparameters of a served decoder."""

    vocab_size: int = 256
    hidden: int = 64
    layers: int = 2
    heads: int = 4
    max_seq_len: int = 256
    ffn_mult: int = 4

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads

    def __post_init__(self):
        if self.hidden % self.heads:
            raise ValueError(
                f"hidden={self.hidden} not divisible by heads={self.heads}")

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModelSpec":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: int(v) for k, v in d.items() if k in names})


def init_params(spec: ModelSpec, seed: int = 0) -> Dict[str, jnp.ndarray]:
    """Flat ``path -> array`` dict (checkpoint-manager friendly)."""
    rng = jax.random.PRNGKey(seed)
    p: Dict[str, jnp.ndarray] = {}

    def _w(key, shape, scale=0.02):
        return (jax.random.normal(key, shape, jnp.float32) * scale)

    keys = jax.random.split(rng, 2 + spec.layers * 6)
    p["embed"] = _w(keys[0], (spec.vocab_size, spec.hidden))
    p["pos"] = _w(keys[1], (spec.max_seq_len, spec.hidden))
    for i in range(spec.layers):
        k = keys[2 + i * 6: 8 + i * 6]
        ffn = spec.hidden * spec.ffn_mult
        p[f"h{i}.ln1.w"] = jnp.ones((spec.hidden,), jnp.float32)
        p[f"h{i}.ln1.b"] = jnp.zeros((spec.hidden,), jnp.float32)
        p[f"h{i}.attn.wq"] = _w(k[0], (spec.hidden, spec.hidden))
        p[f"h{i}.attn.wk"] = _w(k[1], (spec.hidden, spec.hidden))
        p[f"h{i}.attn.wv"] = _w(k[2], (spec.hidden, spec.hidden))
        p[f"h{i}.attn.wo"] = _w(k[3], (spec.hidden, spec.hidden))
        p[f"h{i}.ln2.w"] = jnp.ones((spec.hidden,), jnp.float32)
        p[f"h{i}.ln2.b"] = jnp.zeros((spec.hidden,), jnp.float32)
        p[f"h{i}.mlp.w1"] = _w(k[4], (spec.hidden, ffn))
        p[f"h{i}.mlp.b1"] = jnp.zeros((ffn,), jnp.float32)
        p[f"h{i}.mlp.w2"] = _w(k[5], (ffn, spec.hidden))
        p[f"h{i}.mlp.b2"] = jnp.zeros((spec.hidden,), jnp.float32)
    p["lnf.w"] = jnp.ones((spec.hidden,), jnp.float32)
    p["lnf.b"] = jnp.zeros((spec.hidden,), jnp.float32)
    return p


def _ln(x, w, b):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + _LN_EPS) * w + b


def _mlp(spec, params, i, x, tap=None):
    h = _matmul(params, f"h{i}.mlp.w1", x, tap) + params[f"h{i}.mlp.b1"]
    h = jax.nn.gelu(h)
    return _matmul(params, f"h{i}.mlp.w2", h, tap) + params[f"h{i}.mlp.b2"]


def _flat_dest(page_table, positions, page_size):
    """Flat pool row for each position via its page table.

    ``page_table`` rows hold page ids; position ``t`` lives at flat
    index ``pt[t // ps] * ps + t % ps``.  Works batched (page_table
    (B, maxp), positions (B,)) and single (maxp,)/(S,).
    """
    page = jnp.take_along_axis(
        page_table, (positions // page_size)[..., None], axis=-1)[..., 0] \
        if page_table.ndim == 2 else page_table[positions // page_size]
    return page * page_size + positions % page_size


def prefill_step(spec: ModelSpec, params, k_flat, v_flat,
                 tokens, length, page_table, *, page_size: int,
                 k_scale=None, v_scale=None, tap=None):
    """Run one prompt (padded to a seq bucket) and seed its KV pages.

    Args:
      k_flat/v_flat: donated pools ``(L, P*ps, H, D)``.
      tokens: ``(S,)`` int32, padded prompt (bucket size S).
      length: scalar int32, true prompt length (1 <= length <= S).
      page_table: ``(max_pages,)`` int32 pages owned by this sequence
        (unused tail = 0, the reserved null page).
      page_size: static tokens-per-page (trace-time constant).
      k_scale/v_scale: donated scale pools ``(L, P*ps, H)`` f32 when
        the KV pool is int8 (``k_flat.dtype``); the prompt's K/V are
        quantized per (token, head) at write time.
      tap: optional calibration hook ``tap(site, activation)`` — only
        ever non-None in the eager PTQ harness, never in a serve trace.

    Returns ``(k_flat, v_flat, next_token, logits)``, with the two
    scale pools spliced in after ``v_flat`` when they were passed.
    Prefill attends over the in-layer full-precision K/V (the stored
    pages are for later decode steps), matching standard PTQ serving
    stacks.
    """
    s = tokens.shape[0]
    h = params["embed"][tokens] + params["pos"][:s]
    cdt = params["embed"].dtype
    pos_ids = jnp.arange(s, dtype=jnp.int32)
    # causal AND inside the true prompt: key j visible to query i iff
    # j <= i and j < length
    mask = (pos_ids[None, :] <= pos_ids[:, None]) & (pos_ids[None, :] < length)
    scale = 1.0 / math.sqrt(spec.head_dim)
    ks, vs = [], []
    for i in range(spec.layers):
        x = _ln(h, params[f"h{i}.ln1.w"],
                params[f"h{i}.ln1.b"]).astype(cdt)
        q = _matmul(params, f"h{i}.attn.wq", x,
                    tap).reshape(s, spec.heads, spec.head_dim)
        k = _matmul(params, f"h{i}.attn.wk", x,
                    tap).reshape(s, spec.heads, spec.head_dim)
        v = _matmul(params, f"h{i}.attn.wv", x,
                    tap).reshape(s, spec.heads, spec.head_dim)
        att = jnp.einsum("ihd,jhd->hij", q, k,
                         preferred_element_type=jnp.float32) * scale
        att = jnp.where(mask[None, :, :], att, -1e30)
        w = jax.nn.softmax(att, axis=-1)
        o = jnp.einsum("hij,jhd->ihd", w.astype(v.dtype), v,
                       preferred_element_type=jnp.float32
                       ).reshape(s, spec.hidden).astype(cdt)
        h = h + _matmul(params, f"h{i}.attn.wo", o, tap)
        x2 = _ln(h, params[f"h{i}.ln2.w"],
                 params[f"h{i}.ln2.b"]).astype(cdt)
        h = h + _mlp(spec, params, i, x2, tap)
        ks.append(k)
        vs.append(v)
    hf = _ln(h, params["lnf.w"], params["lnf.b"]).astype(cdt)
    if tap is not None:
        tap("head", hf)
    logits_all = hf @ params["embed"].T                    # (S, V)
    logits = jnp.take(logits_all, length - 1, axis=0)      # (V,)
    next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # scatter prompt K/V into this sequence's pages; padding rows are
    # routed to flat row 0 (inside the reserved null page, never read
    # unmasked)
    dest = jnp.where(pos_ids < length,
                     _flat_dest(page_table, pos_ids, page_size), 0)
    k_stack = jnp.stack(ks)                                # (L, S, H, D)
    v_stack = jnp.stack(vs)
    if k_flat.dtype == jnp.int8:
        kq, ksc = quantize_kv(k_stack)
        vq, vsc = quantize_kv(v_stack)
        k_flat = k_flat.at[:, dest].set(kq)
        v_flat = v_flat.at[:, dest].set(vq)
        k_scale = k_scale.at[:, dest].set(ksc)
        v_scale = v_scale.at[:, dest].set(vsc)
    else:
        k_flat = k_flat.at[:, dest].set(k_stack.astype(k_flat.dtype))
        v_flat = v_flat.at[:, dest].set(v_stack.astype(v_flat.dtype))
    if k_scale is not None:
        return k_flat, v_flat, k_scale, v_scale, next_token, logits
    return k_flat, v_flat, next_token, logits


def decode_step(spec: ModelSpec, params, k_flat, v_flat,
                tokens, positions, page_tables, *, page_size: int,
                k_scale=None, v_scale=None, tap=None):
    """One decode step for a padded batch bucket.

    Args:
      k_flat/v_flat: donated pools ``(L, P*ps, H, D)``.
      tokens: ``(B,)`` int32 current token per row.
      positions: ``(B,)`` int32 position of that token (0-based);
        padding rows point at position 0 with page_table row 0 so
        their writes land in the null page.
      page_tables: ``(B, max_pages)`` int32.
      page_size: static tokens-per-page (trace-time constant).
      k_scale/v_scale: donated scale pools ``(L, P*ps, H)`` f32 for an
        int8 pool; the step's K/V quantize per (token, head) at write
        time — a pure per-row function, so row bytes never depend on
        batch neighbours (the bit-identity contract survives int8).
      tap: optional calibration hook (eager PTQ harness only).

    Returns ``(k_flat, v_flat, next_tokens, logits)``, with the scale
    pools spliced in after ``v_flat`` when they were passed.
    """
    b = tokens.shape[0]
    num_pages = k_flat.shape[1] // page_size
    quant = k_flat.dtype == jnp.int8
    dest = _flat_dest(page_tables, positions, page_size)   # (B,)
    lengths = positions + 1
    h = params["embed"][tokens] + params["pos"][positions]
    cdt = params["embed"].dtype
    for i in range(spec.layers):
        x = _ln(h, params[f"h{i}.ln1.w"],
                params[f"h{i}.ln1.b"]).astype(cdt)
        q = _matmul(params, f"h{i}.attn.wq", x,
                    tap).reshape(b, spec.heads, spec.head_dim)
        k = _matmul(params, f"h{i}.attn.wk", x,
                    tap).reshape(b, spec.heads, spec.head_dim)
        v = _matmul(params, f"h{i}.attn.wv", x,
                    tap).reshape(b, spec.heads, spec.head_dim)
        if quant:
            kq, ksc = quantize_kv(k)
            vq, vsc = quantize_kv(v)
            k_flat = k_flat.at[i, dest].set(kq)
            v_flat = v_flat.at[i, dest].set(vq)
            k_scale = k_scale.at[i, dest].set(ksc)
            v_scale = v_scale.at[i, dest].set(vsc)
            o = paged_attention_int8(
                q,
                k_flat[i].reshape(num_pages, page_size, spec.heads,
                                  spec.head_dim),
                v_flat[i].reshape(num_pages, page_size, spec.heads,
                                  spec.head_dim),
                k_scale[i].reshape(num_pages, page_size, spec.heads),
                v_scale[i].reshape(num_pages, page_size, spec.heads),
                page_tables, lengths)
        else:
            k_flat = k_flat.at[i, dest].set(k.astype(k_flat.dtype))
            v_flat = v_flat.at[i, dest].set(v.astype(v_flat.dtype))
            k_pages = k_flat[i].reshape(num_pages, page_size,
                                        spec.heads, spec.head_dim)
            v_pages = v_flat[i].reshape(num_pages, page_size,
                                        spec.heads, spec.head_dim)
            o = paged_attention(q, k_pages, v_pages, page_tables, lengths)
        h = h + _matmul(params, f"h{i}.attn.wo",
                        o.reshape(b, spec.hidden), tap)
        x2 = _ln(h, params[f"h{i}.ln2.w"],
                 params[f"h{i}.ln2.b"]).astype(cdt)
        h = h + _mlp(spec, params, i, x2, tap)
    hf = _ln(h, params["lnf.w"], params["lnf.b"]).astype(cdt)
    if tap is not None:
        tap("head", hf)
    logits = hf @ params["embed"].T                        # (B, V)
    next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if k_scale is not None:
        return k_flat, v_flat, k_scale, v_scale, next_tokens, logits
    return k_flat, v_flat, next_tokens, logits
