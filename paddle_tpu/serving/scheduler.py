"""Continuous (in-flight) batching over the AOT serve programs.

One scheduler tick = one *step boundary*:

 1. **evict** cancelled and deadline-expired sequences (free pages,
    release reservations, resolve the caller's stream with the error),
 2. **retire** sequences that finished last step (free pages, release
    unused reservations, resolve the caller's stream),
 3. **admit** queued sequences while a decode slot AND worst-case KV
    headroom exist — admission reserves ``ceil((prompt+max_new)/ps)``
    pages up front so an admitted sequence can never stall mid-decode
    waiting for a page (admission control against pool headroom),
 4. **decode** one token for every active row, padded to the smallest
    compiled batch bucket.

Sequences join and leave a *running* batch only at these boundaries,
and the decode math is row-independent (see
:mod:`paddle_tpu.serving.model`), so a sequence's tokens are
bit-identical whether it decoded solo or wove through an ever-changing
batch — the property the continuous-batching tests pin.

Resilience layer (the serving-chaos contract):

 - every request may carry a **deadline** (client-supplied, or the
   server default ``ServeConfig.deadline_ms`` / ``PT_SERVE_DEADLINE_MS``);
   expired requests are evicted at the next step boundary and their
   pages returned — a timed-out caller never leaks KV pages,
 - :meth:`ContinuousScheduler.cancel` (surfaced over HTTP as
   ``POST /v1/cancel``) evicts a request wherever it is — queued or
   mid-decode — again at a step boundary (the scheduler lock IS the
   boundary: decode holds it),
 - **load shedding**: admission refuses requests whose deadline is
   infeasible against measured decode throughput (EWMA of step wall
   time) and the current backlog, and bounds the queue with
   oldest-expired eviction (``pt_serve_shed_total{reason}``),
 - **graceful drain**: :meth:`drain_gracefully` stops admission,
   finishes in-flight decodes within a budget, and cancels the rest
   (``cause="drain"``) — the SIGTERM lifecycle of the HTTP front end,
 - **hang watchdog**: a sentinel thread compares the in-flight decode
   step's wall time against N× the rolling p99; a hung device step
   books a flight dump naming the active batch, degrades ``/healthz``,
   and (``PT_SERVE_WATCHDOG=exit``) fast-exits for supervisor restart.

The whole request path here is numpy + pre-compiled executables; a
single stray jnp call would book an unexpected compile on the
engine's sentinel (tpu-lint TPU019 polices this statically).
"""
from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .kv_cache import KVPoolExhausted

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["ContinuousScheduler", "GenerationStream", "EngineSaturated",
           "RequestShed", "RequestCancelled", "DeadlineExceeded",
           "WATCHDOG_EXIT_CODE"]

# fast-exit status when PT_SERVE_WATCHDOG=exit trips: distinct from the
# drain exit (143) so a supervisor can tell "hung device" from "asked
# to stop" in the restart ledger (canonical taxonomy:
# distributed/exit_codes.py)
from ..distributed.exit_codes import EXIT_WATCHDOG as WATCHDOG_EXIT_CODE  # noqa: E402


class EngineSaturated(RuntimeError):
    """submit() refused: in-flight cap reached (caller should shed load
    or retry with backoff — the HTTP front end maps this to 429)."""


class RequestShed(EngineSaturated):
    """submit() refused by the load shedder.

    ``reason`` is one of ``deadline_infeasible`` (the request cannot
    finish before its deadline given measured throughput + backlog),
    ``queue_full`` (bounded queue at capacity even after evicting
    expired entries), or ``draining`` (SIGTERM lifecycle — admission is
    closed).  ``retry_after`` is the shedder's backlog estimate in
    seconds (the HTTP ``Retry-After`` header)."""

    def __init__(self, message: str, *, reason: str,
                 retry_after: Optional[float] = None):
        super().__init__(message)
        self.reason = reason
        self.retry_after = retry_after


class RequestCancelled(RuntimeError):
    """The request was evicted before completing; ``cause`` is one of
    ``client`` | ``timeout`` | ``disconnect`` | ``drain``."""

    def __init__(self, message: str, *, cause: str = "client"):
        super().__init__(message)
        self.cause = cause


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before it finished decoding; its
    pages were released at the next step boundary."""


class GenerationStream:
    """Future-like handle for one submitted request."""

    _ids = itertools.count()

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 deadline: Optional[float] = None):
        self.request_id = next(self._ids)
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.tokens: List[int] = []
        self.submitted_ts = time.monotonic()
        self.finished_ts: Optional[float] = None
        self.deadline = deadline        # absolute time.monotonic(), or None
        self.cancel_cause: Optional[str] = None
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        self._sched: Optional["ContinuousScheduler"] = None

    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self, cause: str = "client") -> bool:
        """Evict this request (queued or active) at the next step
        boundary, releasing its KV pages.  Returns whether the
        cancellation took effect (False once already finished)."""
        sched = self._sched
        if sched is not None:
            return sched.cancel(self.request_id, cause=cause)
        if not self._done.is_set():
            self.cancel_cause = cause
            self._finish(error=RequestCancelled(
                f"request {self.request_id} cancelled ({cause})",
                cause=cause))
            return True
        return False

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Wait for the final token list.

        A timeout CANCELS the request before raising — the abandoned
        caller must not keep decoding on borrowed KV pages (the page
        leak this layer exists to close)."""
        if not self._done.wait(timeout):
            self.cancel(cause="timeout")
            raise TimeoutError(
                f"request {self.request_id} not finished in {timeout}s")
        if self._error is not None:
            raise self._error
        return self.tokens

    @property
    def latency(self) -> Optional[float]:
        if self.finished_ts is None:
            return None
        return self.finished_ts - self.submitted_ts

    def _finish(self, error: Optional[BaseException] = None) -> None:
        self.finished_ts = time.monotonic()
        self._error = error
        self._done.set()


class _Active:
    """Per-sequence decode state while resident in the batch."""

    __slots__ = ("stream", "page_ids", "page_table", "pos", "last_token",
                 "reserved_left")

    def __init__(self, stream, page_ids, page_table, pos, last_token,
                 reserved_left):
        self.stream = stream
        self.page_ids = page_ids        # owned pages, in position order
        self.page_table = page_table    # np (max_pages,) int32
        self.pos = pos                  # position last_token will occupy
        self.last_token = last_token
        self.reserved_left = reserved_left


class ContinuousScheduler:
    """Admission + step loop; owns the queue and the active batch."""

    def __init__(self, engine):
        self.engine = engine
        self._queue: deque = deque()
        self._active: List[_Active] = []
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # resilience state ---------------------------------------------------
        self._draining = False
        self.hang_detected = False
        self._watchdog_thread: Optional[threading.Thread] = None
        self._step_started: Optional[float] = None  # in-flight decode t0
        self._step_times: deque = deque(maxlen=256)  # rolling wall times
        self._step_ewma: Optional[float] = None      # sec per decode step
        self.stats = {
            "submitted": 0, "completed": 0, "refused_inflight": 0,
            "refused_kv": 0, "steps": 0, "tokens_generated": 0,
            "occupancy_sum": 0.0, "occupancy_steps": 0,
            "peak_active": 0,
            "shed": 0, "cancelled": 0, "deadline_exceeded": 0,
            "failed": 0, "drain_seconds": None, "watchdog_trips": 0,
        }

    # -- submission ----------------------------------------------------------

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> GenerationStream:
        cfg = self.engine.config
        spec = self.engine.spec
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(t < 0 or t >= spec.vocab_size for t in prompt):
            raise ValueError("prompt token out of vocab range")
        self.engine.prefill_bucket_for(len(prompt))  # raises if too long
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else cfg.max_new_tokens)
        max_new = max(1, min(max_new, spec.max_seq_len - len(prompt)))
        if deadline_ms is None:
            deadline_ms = getattr(cfg, "deadline_ms", 0.0)
        deadline_ms = float(deadline_ms or 0.0)
        if deadline_ms < 0:
            raise ValueError("deadline_ms must be >= 0")
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms > 0 else None)
        with self._cv:
            if self._draining:
                self._shed_locked("draining")
                raise RequestShed("engine draining — admission closed",
                                  reason="draining")
            inflight = len(self._queue) + len(self._active)
            if inflight >= cfg.max_inflight:
                self.stats["refused_inflight"] += 1
                self._book("pt_serve_admission_refusals_total",
                           kind="counter", reason="inflight_cap")
                raise EngineSaturated(
                    f"{inflight} requests in flight (cap "
                    f"{cfg.max_inflight})")
            max_queue = int(getattr(cfg, "max_queue", 0) or 0)
            if max_queue > 0 and len(self._queue) >= max_queue:
                # bounded queue: make room by evicting already-expired
                # entries (oldest first) before refusing fresh work
                self._expire_queue_locked()
                if len(self._queue) >= max_queue:
                    eta = self._backlog_eta_locked()
                    self._shed_locked("queue_full")
                    raise RequestShed(
                        f"queue full ({max_queue} waiting)",
                        reason="queue_full", retry_after=eta)
            if deadline is not None:
                eta = self._completion_eta_locked(max_new)
                if eta is not None and time.monotonic() + eta > deadline:
                    self._shed_locked("deadline_infeasible")
                    raise RequestShed(
                        f"deadline {deadline_ms:.0f}ms infeasible: "
                        f"estimated completion in {eta * 1e3:.0f}ms",
                        reason="deadline_infeasible",
                        retry_after=self._backlog_eta_locked())
            st = GenerationStream(prompt, max_new, deadline=deadline)
            st._sched = self
            self._queue.append(st)
            self.stats["submitted"] += 1
            self._book("pt_serve_requests_total", kind="counter")
            self._gauges_locked()
            self._cv.notify()
        return st

    def _shed_locked(self, reason: str) -> None:
        self.stats["shed"] += 1
        self._book("pt_serve_shed_total", kind="counter", reason=reason)

    def _completion_eta_locked(self, max_new: int) -> Optional[float]:
        """Seconds until a request submitted NOW would finish, from the
        measured step-time EWMA and the token backlog ahead of it.
        None until throughput has been measured (admit optimistically)."""
        ew = self._step_ewma
        if ew is None:
            return None
        return self._backlog_eta_locked() + ew * (max_new + 1)

    def _backlog_eta_locked(self) -> Optional[float]:
        ew = self._step_ewma
        if ew is None:
            return None
        backlog = sum(st.max_new_tokens for st in self._queue)
        backlog += sum(
            max(0, a.stream.max_new_tokens - len(a.stream.tokens))
            for a in self._active)
        max_batch = self.engine.config.decode_buckets[-1]
        return ew * (backlog / max(1, max_batch))

    # -- cancellation / eviction ---------------------------------------------

    def cancel(self, request_id: int, cause: str = "client") -> bool:
        """Evict a request wherever it is.  Taking the scheduler lock
        IS the step boundary — decode holds it — so an active row is
        removed between steps, never mid-kernel."""
        with self._cv:
            for st in self._queue:
                if st.request_id == request_id:
                    self._queue.remove(st)
                    self._finish_evicted_locked(st, cause)
                    self._gauges_locked()
                    return True
            for a in self._active:
                if a.stream.request_id == request_id:
                    self._active.remove(a)
                    self._release_locked(a)
                    self._finish_evicted_locked(a.stream, cause)
                    self._gauges_locked()
                    return True
        return False

    def _release_locked(self, a: _Active) -> None:
        pool = self.engine.pool
        pool.free(a.page_ids)
        if a.reserved_left:
            pool.release_reservation(a.reserved_left)

    def _finish_evicted_locked(self, st: GenerationStream,
                               cause: str) -> None:
        st.cancel_cause = cause
        if cause == "deadline":
            self.stats["deadline_exceeded"] += 1
            self._book("pt_serve_deadline_exceeded_total", kind="counter")
            err: BaseException = DeadlineExceeded(
                f"request {st.request_id} missed its deadline after "
                f"{len(st.tokens)}/{st.max_new_tokens} tokens")
        else:
            err = RequestCancelled(
                f"request {st.request_id} cancelled ({cause})",
                cause=cause)
        self.stats["cancelled"] += 1
        self._book("pt_serve_cancelled_total", kind="counter", cause=cause)
        st._finish(error=err)

    def _expire_queue_locked(self) -> None:
        now = time.monotonic()
        expired = [st for st in self._queue
                   if st.deadline is not None and now >= st.deadline]
        for st in expired:
            self._queue.remove(st)
            self._finish_evicted_locked(st, "deadline")

    def _evict_expired_locked(self) -> None:
        """Deadline sweep at the step boundary: queued AND active."""
        self._expire_queue_locked()
        now = time.monotonic()
        expired = [a for a in self._active
                   if a.stream.deadline is not None
                   and now >= a.stream.deadline]
        for a in expired:
            self._active.remove(a)
            self._release_locked(a)
            self._finish_evicted_locked(a.stream, "deadline")

    # -- the step loop -------------------------------------------------------

    def step(self) -> bool:
        """One step boundary: evict / retire / admit / decode.  Returns
        whether any work was done."""
        with self._lock:
            self._evict_expired_locked()
            # draining closes submit(), not the internal queue: every
            # request accepted before SIGTERM still owes a response
            self._admit_locked()
            worked = self._decode_locked()
            self.stats["steps"] += 1 if worked else 0
            self._gauges_locked()
            return worked or bool(self._queue)

    def _admit_locked(self) -> None:
        pool = self.engine.pool
        max_batch = self.engine.config.decode_buckets[-1]
        while self._queue and len(self._active) < max_batch:
            st = self._queue[0]
            worst_case = pool.pages_needed(len(st.prompt) + st.max_new_tokens)
            if not pool.can_admit(worst_case):
                # head-of-line blocking is deliberate: skipping ahead
                # would starve large requests under sustained load
                self.stats["refused_kv"] += 1
                self._book("pt_serve_admission_refusals_total",
                           kind="counter", reason="kv_headroom")
                break
            self._queue.popleft()
            try:
                pool.reserve(worst_case)
            except KVPoolExhausted:
                self.stats["refused_kv"] += 1
                self._queue.appendleft(st)
                break
            prompt_pages = pool.pages_needed(len(st.prompt))
            page_ids = pool.alloc(prompt_pages, reserved=True)
            reserved_left = worst_case - prompt_pages
            page_table = pool.null_padded_table(
                page_ids, self.engine.max_pages_per_seq)
            try:
                first = self.engine.prefill(st.prompt, page_table)
            except Exception as exc:  # resolve the caller, keep serving
                pool.free(page_ids)
                pool.release_reservation(reserved_left)
                self.stats["failed"] += 1
                self._book("pt_serve_request_failures_total",
                           kind="counter", stage="prefill")
                st._finish(error=exc)
                logger.exception("prefill failed for request %d",
                                 st.request_id)
                continue
            st.tokens.append(first)
            self._book("pt_serve_tokens_total", kind="counter")
            self.stats["tokens_generated"] += 1
            act = _Active(st, page_ids, page_table, pos=len(st.prompt),
                          last_token=first, reserved_left=reserved_left)
            if self._is_finished(act):
                self._retire_locked(act)
            else:
                self._active.append(act)
                self.stats["peak_active"] = max(
                    self.stats["peak_active"], len(self._active))

    def _decode_locked(self) -> bool:
        if not self._active:
            return False
        pool = self.engine.pool
        ps = self.engine.config.page_size
        # grow page tables for rows whose next write crosses a page
        # boundary — drawn from the admission-time reservation, so this
        # alloc cannot fail
        for a in self._active:
            need = a.pos // ps + 1
            if need > len(a.page_ids):
                new = pool.alloc(need - len(a.page_ids), reserved=True)
                for pid in new:
                    a.page_table[len(a.page_ids)] = pid
                    a.page_ids.append(pid)
                a.reserved_left -= len(new)
        n = len(self._active)
        tokens = np.asarray([a.last_token for a in self._active], np.int32)
        positions = np.asarray([a.pos for a in self._active], np.int32)
        tables = np.stack([a.page_table for a in self._active])
        t0 = time.monotonic()
        self._step_started = t0  # watchdog arms on the device call
        try:
            nxt = self.engine.decode(tokens, positions, tables)
        except Exception as exc:
            # a failed device step fails every resident request — with
            # their pages RETURNED — and the loop keeps serving the
            # queue; one poisoned batch must not wedge the engine
            self._step_started = None
            self._fail_batch_locked(exc)
            return True
        finally:
            self._step_started = None
        dt = time.monotonic() - t0
        self._step_times.append(dt)
        self._step_ewma = (dt if self._step_ewma is None
                           else 0.2 * dt + 0.8 * self._step_ewma)
        bucket = self.engine.decode_bucket_for(n)
        self.stats["occupancy_sum"] += n / bucket
        self.stats["occupancy_steps"] += 1
        self._book("pt_serve_batch_occupancy", kind="gauge",
                   value=n / bucket)
        still = []
        for a, t in zip(self._active, nxt):
            try:
                a.pos += 1
                a.last_token = int(t)
                a.stream.tokens.append(int(t))
                self.stats["tokens_generated"] += 1
                self._book("pt_serve_tokens_total", kind="counter")
                if self._is_finished(a):
                    self._retire_locked(a)
                else:
                    still.append(a)
            except Exception as exc:
                # per-row isolation: this request fails alone; its
                # neighbours keep decoding and its pages come back
                self._release_locked(a)
                self.stats["failed"] += 1
                self._book("pt_serve_request_failures_total",
                           kind="counter", stage="step")
                a.stream._finish(error=exc)
                logger.exception("step bookkeeping failed for request %d",
                                 a.stream.request_id)
        self._active = still
        return True

    def _fail_batch_locked(self, exc: BaseException) -> None:
        for a in self._active:
            self._release_locked(a)
            self.stats["failed"] += 1
            self._book("pt_serve_request_failures_total",
                       kind="counter", stage="decode")
            a.stream._finish(error=exc)
        logger.exception("decode step failed; %d requests failed, pages "
                         "released", len(self._active))
        self._active = []

    def _is_finished(self, a: _Active) -> bool:
        st = a.stream
        if len(st.tokens) >= st.max_new_tokens:
            return True
        eos = self.engine.config.eos_id
        return eos >= 0 and a.last_token == eos

    def _retire_locked(self, a: _Active) -> None:
        pool = self.engine.pool
        pool.free(a.page_ids)
        if a.reserved_left:
            pool.release_reservation(a.reserved_left)
        a.stream._finish()
        self.stats["completed"] += 1
        lat = a.stream.latency
        self._book("pt_serve_request_latency_seconds", kind="histogram",
                   value=lat)
        self._book("pt_serve_completed_total", kind="counter")

    # -- loop management -----------------------------------------------------

    def start(self) -> None:
        """Run the step loop on a background thread (HTTP-serving mode).
        Also arms the hang watchdog when ``PT_SERVE_WATCHDOG`` asks for
        it."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="pt-serve-scheduler", daemon=True)
            self._thread.start()
        self._start_watchdog()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
        w = self._watchdog_thread
        if w is not None:
            w.join(timeout)
            self._watchdog_thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                while (not self._queue and not self._active
                       and not self._stop.is_set()):
                    self._cv.wait(0.05)
            if self._stop.is_set():
                return
            try:
                self.step()
            except Exception:
                logger.exception("scheduler step failed")
                time.sleep(0.01)

    def drain(self) -> None:
        """Block until queue and batch are empty.  Steps inline when no
        background loop is running (synchronous/generate mode)."""
        if self._thread is not None and self._thread.is_alive():
            while True:
                with self._lock:
                    if not self._queue and not self._active:
                        return
                time.sleep(0.002)
        while True:
            with self._lock:
                if not self._queue and not self._active:
                    return
            self.step()

    # -- graceful drain (SIGTERM lifecycle) ----------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Close admission: every subsequent submit sheds with
        ``reason="draining"`` and ``/healthz`` degrades so load
        balancers stop routing here."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()

    def drain_gracefully(self, budget_s: Optional[float] = None) -> bool:
        """Stop admission, finish in-flight work within ``budget_s``
        (default ``ServeConfig.drain_s``), then cancel whatever is left
        with ``cause="drain"``.  Returns True when everything finished
        inside the budget (no request was cut short)."""
        t0 = time.monotonic()
        self.begin_drain()
        if budget_s is None:
            budget_s = float(getattr(self.engine.config, "drain_s", 10.0))
        loop_running = (self._thread is not None
                        and self._thread.is_alive())
        while time.monotonic() - t0 < budget_s:
            with self._lock:
                if not self._queue and not self._active:
                    break
            if loop_running:
                time.sleep(0.01)
            else:
                self.step()
        clean = True
        with self._cv:
            leftovers = list(self._queue)
            self._queue.clear()
            for st in leftovers:
                clean = False
                self._finish_evicted_locked(st, "drain")
            for a in list(self._active):
                clean = False
                self._active.remove(a)
                self._release_locked(a)
                self._finish_evicted_locked(a.stream, "drain")
            self._gauges_locked()
        dur = time.monotonic() - t0
        self.stats["drain_seconds"] = dur
        self._book("pt_serve_drain_seconds", kind="gauge", value=dur)
        logger.info("graceful drain %s in %.3fs",
                    "completed" if clean else
                    "cut short (budget exhausted)", dur)
        return clean

    # -- hang watchdog --------------------------------------------------------

    @staticmethod
    def _watchdog_mode() -> Optional[str]:
        mode = os.environ.get("PT_SERVE_WATCHDOG", "").strip().lower()
        if mode in ("", "0", "off", "false", "no"):
            return None
        return "exit" if mode == "exit" else "on"

    def _start_watchdog(self) -> None:
        mode = self._watchdog_mode()
        if mode is None:
            return
        if (self._watchdog_thread is not None
                and self._watchdog_thread.is_alive()):
            return
        factor = float(os.environ.get("PT_SERVE_WATCHDOG_FACTOR", "20"))
        floor = float(os.environ.get("PT_SERVE_WATCHDOG_FLOOR_S", "1.0"))
        self._watchdog_thread = threading.Thread(
            target=self._watchdog_loop, args=(mode, factor, floor),
            name="pt-serve-watchdog", daemon=True)
        self._watchdog_thread.start()

    def _watchdog_loop(self, mode: str, factor: float,
                       floor: float) -> None:
        poll = max(0.02, min(0.25, floor / 4))
        while not self._stop.wait(poll):
            started = self._step_started
            if started is None:
                continue
            times = list(self._step_times)
            p99 = float(np.percentile(times, 99)) if times else None
            threshold = max(floor, factor * p99) if p99 else floor
            stuck = time.monotonic() - started
            if stuck > threshold:
                self._trip_watchdog(mode, stuck, threshold)
                return

    def _trip_watchdog(self, mode: str, stuck: float,
                       threshold: float) -> None:
        """The in-flight decode step is hung (NOT merely loaded: the
        threshold tracks the rolling p99).  Runs WITHOUT the scheduler
        lock — the hung step is holding it."""
        self.hang_detected = True
        self.stats["watchdog_trips"] += 1
        try:
            rids = [a.stream.request_id for a in list(self._active)]
        except Exception:
            rids = []
        logger.error(
            "serve hang watchdog tripped: decode step in flight for "
            "%.3fs (threshold %.3fs); active batch %s",
            stuck, threshold, rids)
        self._book("pt_serve_hang_watchdog_trips_total", kind="counter")
        try:
            from ..observability.trace import get_tracer
            get_tracer().flight_dump(
                reason="serve-hang rid=%s stuck=%.3fs" %
                (",".join(map(str, rids)) or "-", stuck))
        except Exception:
            pass
        if mode == "exit":
            logger.error("PT_SERVE_WATCHDOG=exit: fast-exiting %d for "
                         "supervisor restart", WATCHDOG_EXIT_CODE)
            os._exit(WATCHDOG_EXIT_CODE)

    # -- observability -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            occ = (self.stats["occupancy_sum"] /
                   max(1, self.stats["occupancy_steps"]))
            return {
                "queue_depth": len(self._queue),
                "active_sequences": len(self._active),
                "batch_occupancy_mean": occ,
                "draining": self._draining,
                "hang_detected": self.hang_detected,
                "decode_step_ewma_s": self._step_ewma,
                **{k: v for k, v in self.stats.items()
                   if k not in ("occupancy_sum",)},
            }

    def _gauges_locked(self) -> None:
        self._book("pt_serve_queue_depth", kind="gauge",
                   value=len(self._queue))
        self._book("pt_serve_active_sequences", kind="gauge",
                   value=len(self._active))

    def _book(self, name: str, *, kind: str, value: float = 1.0,
              **labels) -> None:
        """Metric booking; inert while telemetry is off (registry must
        stay empty then)."""
        try:
            from ..observability.metrics import get_registry
            from ..observability.telemetry import get_telemetry
            if not get_telemetry().enabled:
                return
            reg = get_registry()
            help_ = _METRIC_HELP.get(name, "")
            if kind == "counter":
                reg.counter(name, help_,
                            labelnames=tuple(labels)).inc(value, **labels)
            elif kind == "gauge":
                reg.gauge(name, help_,
                          labelnames=tuple(labels)).set(value, **labels)
            else:
                reg.histogram(name, help_,
                              labelnames=tuple(labels)).observe(
                    value, **labels)
        except Exception:
            pass


_METRIC_HELP = {
    "pt_serve_requests_total": "Requests accepted by the serve scheduler",
    "pt_serve_completed_total": "Requests completed",
    "pt_serve_admission_refusals_total":
        "Admissions refused, by reason (inflight_cap|kv_headroom)",
    "pt_serve_shed_total":
        "Requests shed at admission, by reason "
        "(deadline_infeasible|queue_full|draining)",
    "pt_serve_cancelled_total":
        "Requests evicted before completing, by cause "
        "(client|timeout|deadline|disconnect|drain)",
    "pt_serve_deadline_exceeded_total":
        "Requests that missed their deadline (shed or evicted)",
    "pt_serve_drain_seconds":
        "Wall time of the last graceful drain",
    "pt_serve_request_failures_total":
        "Requests failed by an exception in the step loop, by stage "
        "(prefill|decode|step)",
    "pt_serve_hang_watchdog_trips_total":
        "Hang-watchdog trips (decode step exceeded Nx rolling p99)",
    "pt_serve_tokens_total": "Tokens generated by the serve engine",
    "pt_serve_queue_depth": "Requests waiting for admission",
    "pt_serve_active_sequences": "Sequences resident in the decode batch",
    "pt_serve_batch_occupancy":
        "Active rows / decode bucket size of the last step",
    "pt_serve_request_latency_seconds":
        "End-to-end request latency (submit to last token)",
}
