"""Continuous (in-flight) batching over the AOT serve programs.

One scheduler tick = one *step boundary*:

 1. **retire** sequences that finished last step (free pages, release
    unused reservations, resolve the caller's stream),
 2. **admit** queued sequences while a decode slot AND worst-case KV
    headroom exist — admission reserves ``ceil((prompt+max_new)/ps)``
    pages up front so an admitted sequence can never stall mid-decode
    waiting for a page (admission control against pool headroom),
 3. **decode** one token for every active row, padded to the smallest
    compiled batch bucket.

Sequences join and leave a *running* batch only at these boundaries,
and the decode math is row-independent (see
:mod:`paddle_tpu.serving.model`), so a sequence's tokens are
bit-identical whether it decoded solo or wove through an ever-changing
batch — the property the continuous-batching tests pin.

The whole request path here is numpy + pre-compiled executables; a
single stray jnp call would book an unexpected compile on the
engine's sentinel (tpu-lint TPU019 polices this statically).
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .kv_cache import KVPoolExhausted

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["ContinuousScheduler", "GenerationStream", "EngineSaturated"]


class EngineSaturated(RuntimeError):
    """submit() refused: in-flight cap reached (caller should shed load
    or retry with backoff — the HTTP front end maps this to 429)."""


class GenerationStream:
    """Future-like handle for one submitted request."""

    _ids = itertools.count()

    def __init__(self, prompt: List[int], max_new_tokens: int):
        self.request_id = next(self._ids)
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.tokens: List[int] = []
        self.submitted_ts = time.monotonic()
        self.finished_ts: Optional[float] = None
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished in {timeout}s")
        if self._error is not None:
            raise self._error
        return self.tokens

    @property
    def latency(self) -> Optional[float]:
        if self.finished_ts is None:
            return None
        return self.finished_ts - self.submitted_ts

    def _finish(self, error: Optional[BaseException] = None) -> None:
        self.finished_ts = time.monotonic()
        self._error = error
        self._done.set()


class _Active:
    """Per-sequence decode state while resident in the batch."""

    __slots__ = ("stream", "page_ids", "page_table", "pos", "last_token",
                 "reserved_left")

    def __init__(self, stream, page_ids, page_table, pos, last_token,
                 reserved_left):
        self.stream = stream
        self.page_ids = page_ids        # owned pages, in position order
        self.page_table = page_table    # np (max_pages,) int32
        self.pos = pos                  # position last_token will occupy
        self.last_token = last_token
        self.reserved_left = reserved_left


class ContinuousScheduler:
    """Admission + step loop; owns the queue and the active batch."""

    def __init__(self, engine):
        self.engine = engine
        self._queue: deque = deque()
        self._active: List[_Active] = []
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats = {
            "submitted": 0, "completed": 0, "refused_inflight": 0,
            "refused_kv": 0, "steps": 0, "tokens_generated": 0,
            "occupancy_sum": 0.0, "occupancy_steps": 0,
            "peak_active": 0,
        }

    # -- submission ----------------------------------------------------------

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None) -> GenerationStream:
        cfg = self.engine.config
        spec = self.engine.spec
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(t < 0 or t >= spec.vocab_size for t in prompt):
            raise ValueError("prompt token out of vocab range")
        self.engine.prefill_bucket_for(len(prompt))  # raises if too long
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else cfg.max_new_tokens)
        max_new = max(1, min(max_new, spec.max_seq_len - len(prompt)))
        with self._cv:
            inflight = len(self._queue) + len(self._active)
            if inflight >= cfg.max_inflight:
                self.stats["refused_inflight"] += 1
                self._book("pt_serve_admission_refusals_total",
                           kind="counter", reason="inflight_cap")
                raise EngineSaturated(
                    f"{inflight} requests in flight (cap "
                    f"{cfg.max_inflight})")
            st = GenerationStream(prompt, max_new)
            self._queue.append(st)
            self.stats["submitted"] += 1
            self._book("pt_serve_requests_total", kind="counter")
            self._gauges_locked()
            self._cv.notify()
        return st

    # -- the step loop -------------------------------------------------------

    def step(self) -> bool:
        """One step boundary: retire / admit / decode.  Returns whether
        any work was done."""
        with self._lock:
            self._admit_locked()
            worked = self._decode_locked()
            self.stats["steps"] += 1 if worked else 0
            self._gauges_locked()
            return worked or bool(self._queue)

    def _admit_locked(self) -> None:
        pool = self.engine.pool
        max_batch = self.engine.config.decode_buckets[-1]
        while self._queue and len(self._active) < max_batch:
            st = self._queue[0]
            worst_case = pool.pages_needed(len(st.prompt) + st.max_new_tokens)
            if not pool.can_admit(worst_case):
                # head-of-line blocking is deliberate: skipping ahead
                # would starve large requests under sustained load
                self.stats["refused_kv"] += 1
                self._book("pt_serve_admission_refusals_total",
                           kind="counter", reason="kv_headroom")
                break
            self._queue.popleft()
            try:
                pool.reserve(worst_case)
            except KVPoolExhausted:
                self.stats["refused_kv"] += 1
                self._queue.appendleft(st)
                break
            prompt_pages = pool.pages_needed(len(st.prompt))
            page_ids = pool.alloc(prompt_pages, reserved=True)
            reserved_left = worst_case - prompt_pages
            page_table = pool.null_padded_table(
                page_ids, self.engine.max_pages_per_seq)
            try:
                first = self.engine.prefill(st.prompt, page_table)
            except Exception as exc:  # resolve the caller, keep serving
                pool.free(page_ids)
                pool.release_reservation(reserved_left)
                st._finish(error=exc)
                logger.exception("prefill failed for request %d",
                                 st.request_id)
                continue
            st.tokens.append(first)
            self._book("pt_serve_tokens_total", kind="counter")
            self.stats["tokens_generated"] += 1
            act = _Active(st, page_ids, page_table, pos=len(st.prompt),
                          last_token=first, reserved_left=reserved_left)
            if self._is_finished(act):
                self._retire_locked(act)
            else:
                self._active.append(act)
                self.stats["peak_active"] = max(
                    self.stats["peak_active"], len(self._active))

    def _decode_locked(self) -> bool:
        if not self._active:
            return False
        pool = self.engine.pool
        ps = self.engine.config.page_size
        # grow page tables for rows whose next write crosses a page
        # boundary — drawn from the admission-time reservation, so this
        # alloc cannot fail
        for a in self._active:
            need = a.pos // ps + 1
            if need > len(a.page_ids):
                new = pool.alloc(need - len(a.page_ids), reserved=True)
                for pid in new:
                    a.page_table[len(a.page_ids)] = pid
                    a.page_ids.append(pid)
                a.reserved_left -= len(new)
        n = len(self._active)
        tokens = np.asarray([a.last_token for a in self._active], np.int32)
        positions = np.asarray([a.pos for a in self._active], np.int32)
        tables = np.stack([a.page_table for a in self._active])
        nxt = self.engine.decode(tokens, positions, tables)
        bucket = self.engine.decode_bucket_for(n)
        self.stats["occupancy_sum"] += n / bucket
        self.stats["occupancy_steps"] += 1
        self._book("pt_serve_batch_occupancy", kind="gauge",
                   value=n / bucket)
        still = []
        for a, t in zip(self._active, nxt):
            a.pos += 1
            a.last_token = int(t)
            a.stream.tokens.append(int(t))
            self.stats["tokens_generated"] += 1
            self._book("pt_serve_tokens_total", kind="counter")
            if self._is_finished(a):
                self._retire_locked(a)
            else:
                still.append(a)
        self._active = still
        return True

    def _is_finished(self, a: _Active) -> bool:
        st = a.stream
        if len(st.tokens) >= st.max_new_tokens:
            return True
        eos = self.engine.config.eos_id
        return eos >= 0 and a.last_token == eos

    def _retire_locked(self, a: _Active) -> None:
        pool = self.engine.pool
        pool.free(a.page_ids)
        if a.reserved_left:
            pool.release_reservation(a.reserved_left)
        a.stream._finish()
        self.stats["completed"] += 1
        lat = a.stream.latency
        self._book("pt_serve_request_latency_seconds", kind="histogram",
                   value=lat)
        self._book("pt_serve_completed_total", kind="counter")

    # -- loop management -----------------------------------------------------

    def start(self) -> None:
        """Run the step loop on a background thread (HTTP-serving mode)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="pt-serve-scheduler", daemon=True)
            self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                while (not self._queue and not self._active
                       and not self._stop.is_set()):
                    self._cv.wait(0.05)
            if self._stop.is_set():
                return
            try:
                self.step()
            except Exception:
                logger.exception("scheduler step failed")
                time.sleep(0.01)

    def drain(self) -> None:
        """Block until queue and batch are empty.  Steps inline when no
        background loop is running (synchronous/generate mode)."""
        if self._thread is not None and self._thread.is_alive():
            while True:
                with self._lock:
                    if not self._queue and not self._active:
                        return
                time.sleep(0.002)
        while True:
            with self._lock:
                if not self._queue and not self._active:
                    return
            self.step()

    # -- observability -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            occ = (self.stats["occupancy_sum"] /
                   max(1, self.stats["occupancy_steps"]))
            return {
                "queue_depth": len(self._queue),
                "active_sequences": len(self._active),
                "batch_occupancy_mean": occ,
                **{k: v for k, v in self.stats.items()
                   if k not in ("occupancy_sum",)},
            }

    def _gauges_locked(self) -> None:
        self._book("pt_serve_queue_depth", kind="gauge",
                   value=len(self._queue))
        self._book("pt_serve_active_sequences", kind="gauge",
                   value=len(self._active))

    def _book(self, name: str, *, kind: str, value: float = 1.0,
              **labels) -> None:
        """Metric booking; inert while telemetry is off (registry must
        stay empty then)."""
        try:
            from ..observability.metrics import get_registry
            from ..observability.telemetry import get_telemetry
            if not get_telemetry().enabled:
                return
            reg = get_registry()
            help_ = _METRIC_HELP.get(name, "")
            if kind == "counter":
                reg.counter(name, help_,
                            labelnames=tuple(labels)).inc(value, **labels)
            elif kind == "gauge":
                reg.gauge(name, help_,
                          labelnames=tuple(labels)).set(value, **labels)
            else:
                reg.histogram(name, help_,
                              labelnames=tuple(labels)).observe(
                    value, **labels)
        except Exception:
            pass


_METRIC_HELP = {
    "pt_serve_requests_total": "Requests accepted by the serve scheduler",
    "pt_serve_completed_total": "Requests completed",
    "pt_serve_admission_refusals_total":
        "Admissions refused, by reason (inflight_cap|kv_headroom)",
    "pt_serve_tokens_total": "Tokens generated by the serve engine",
    "pt_serve_queue_depth": "Requests waiting for admission",
    "pt_serve_active_sequences": "Sequences resident in the decode batch",
    "pt_serve_batch_occupancy":
        "Active rows / decode bucket size of the last step",
    "pt_serve_request_latency_seconds":
        "End-to-end request latency (submit to last token)",
}
