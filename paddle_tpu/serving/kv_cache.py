"""Paged KV-cache: a block pool of fixed-size pages with free-list reuse.

The pool owns the device arrays the decode/prefill programs donate and
rebind each step (``k_flat``/``v_flat``, shape ``(L, P*ps, H, D)``), a
host-side free list of page ids, and a *reservation* ledger used for
admission control: the scheduler reserves a sequence's worst-case page
count (prompt + max_new_tokens) before prefill so a sequence admitted
into the batch can never stall mid-decode waiting for a page.

Page 0 is reserved as the **null page**: padding rows of a batch
bucket and the unused tail of every page table point at it, so the
programs' scatter/gather of padding lanes touch real (never-read)
storage instead of needing per-lane predication.

Observability rides the PR 14 rails: when telemetry is on, occupancy
gauges (``pt_serve_kv_pages{state=used|free|reserved}``) are updated on
every alloc/free, and the pool registers a live-buffer attribution
provider so the memory census names the pools ``kv::k_pages`` /
``kv::v_pages``.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Sequence

import jax.numpy as jnp
import numpy as np

__all__ = ["PagePool", "KVPoolExhausted", "NULL_PAGE", "kv_page_budget"]

NULL_PAGE = 0


def kv_page_budget(pages: int, precision: str, head_dim: int) -> int:
    """Scale an fp32-denominated page budget to a precision's real cost.

    ``PT_SERVE_KV_PAGES`` is a BYTE budget expressed in fp32 pages (so
    deployments compare precisions at identical HBM spend).  Per
    (token, head) an fp32 page row costs ``4*D`` bytes; bf16 halves it;
    int8 costs ``D`` for the values plus 4 for the f32 scale riding in
    the scale pages.  The null page scales with everything else, so the
    *usable* count is what gets the ratio — int8 at D=16 yields 3.2x
    the admission headroom at the same byte spend.
    """
    if precision in ("fp32", "float32"):
        return pages
    fp32_cost = 4.0 * head_dim
    if precision in ("bf16", "bfloat16"):
        cost = 2.0 * head_dim
    elif precision == "int8":
        cost = head_dim + 4.0
    else:
        raise ValueError(f"unknown serve precision {precision!r}")
    return 1 + int((pages - 1) * fp32_cost / cost)


class KVPoolExhausted(RuntimeError):
    """Raised when an alloc/reserve exceeds pool headroom."""


class PagePool:
    """Block-pool allocator over the serve KV arrays.

    Thread-safety: all bookkeeping is lock-guarded; the device arrays
    themselves are only rebound from the engine's step loop.
    """

    def __init__(self, *, layers: int, pages: int, page_size: int,
                 heads: int, head_dim: int, dtype=jnp.float32,
                 scale_pages: bool = False):
        if pages < 2:
            raise ValueError("pages must be >= 2 (page 0 is the null page)")
        self.layers = layers
        self.pages = pages
        self.page_size = page_size
        self.heads = heads
        self.head_dim = head_dim
        self.dtype = dtype
        # quantized pools carry per-(token, head) f32 scales in shadow
        # "scale pages" addressed by the same page table (the scale
        # travels with the tensor — the TPU022 contract)
        self.scale_pages = bool(scale_pages)
        shape = (layers, pages * page_size, heads, head_dim)
        self.k_flat = jnp.zeros(shape, dtype)
        self.v_flat = jnp.zeros(shape, dtype)
        sshape = (layers, pages * page_size, heads)
        self.k_scale = jnp.zeros(sshape, jnp.float32) \
            if self.scale_pages else None
        self.v_scale = jnp.zeros(sshape, jnp.float32) \
            if self.scale_pages else None
        self._lock = threading.Lock()
        # LIFO free list: hot pages get reused while still cache/HBM warm
        self._free: List[int] = list(range(pages - 1, 0, -1))
        self._reserved = 0
        self.stats = {
            "allocs": 0, "frees": 0, "alloc_failures": 0,
            "reserve_refusals": 0, "high_watermark": 0,
        }
        self._register_memory_provider()

    # -- capacity ----------------------------------------------------------

    @property
    def usable_pages(self) -> int:
        return self.pages - 1  # minus the null page

    def pages_needed(self, tokens: int) -> int:
        return max(1, -(-int(tokens) // self.page_size))

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        with self._lock:
            return self.usable_pages - len(self._free)

    @property
    def reserved_pages(self) -> int:
        with self._lock:
            return self._reserved

    def headroom(self) -> int:
        """Pages available to NEW admissions (free minus already promised)."""
        with self._lock:
            return len(self._free) - self._reserved

    # -- admission-control reservations ------------------------------------

    def can_admit(self, n_pages: int) -> bool:
        return self.headroom() >= n_pages

    def reserve(self, n_pages: int) -> None:
        """Promise ``n_pages`` to a sequence about to be admitted."""
        with self._lock:
            if len(self._free) - self._reserved < n_pages:
                self.stats["reserve_refusals"] += 1
                raise KVPoolExhausted(
                    f"reserve({n_pages}): only "
                    f"{len(self._free) - self._reserved} unreserved pages")
            self._reserved += n_pages
        self._gauges()

    def release_reservation(self, n_pages: int) -> None:
        """Return unused promised pages (sequence finished early)."""
        with self._lock:
            self._reserved = max(0, self._reserved - n_pages)
        self._gauges()

    # -- alloc / free -------------------------------------------------------

    def alloc(self, n_pages: int = 1, *, reserved: bool = False) -> List[int]:
        """Pop ``n_pages`` page ids off the free list.

        ``reserved=True`` draws down a prior :meth:`reserve` promise
        (the scheduler's path); an unreserved alloc can fail even when
        pages are free if they are all promised elsewhere.
        """
        with self._lock:
            avail = len(self._free) if reserved \
                else len(self._free) - self._reserved
            if avail < n_pages:
                self.stats["alloc_failures"] += 1
                raise KVPoolExhausted(
                    f"alloc({n_pages}): {avail} pages available")
            ids = [self._free.pop() for _ in range(n_pages)]
            if reserved:
                self._reserved = max(0, self._reserved - n_pages)
            self.stats["allocs"] += n_pages
            used = self.usable_pages - len(self._free)
            self.stats["high_watermark"] = max(
                self.stats["high_watermark"], used)
        self._gauges()
        return ids

    def free(self, page_ids: Sequence[int]) -> None:
        """Return a retired sequence's pages to the free list."""
        with self._lock:
            for pid in page_ids:
                if pid == NULL_PAGE:
                    raise ValueError("cannot free the null page")
                if not (0 < pid < self.pages):
                    raise ValueError(f"page id {pid} out of range")
                if pid in self._free:
                    raise ValueError(f"double free of page {pid}")
                self._free.append(pid)
            self.stats["frees"] += len(page_ids)
        self._gauges()

    def check_consistency(self, expect_all_free: bool = False) -> None:
        """Invariant check used by tests and the serve chaos drills:
        no duplicate/lost pages.  ``expect_all_free=True`` additionally
        proves a clean slate — every usable page back on the free list
        and zero outstanding reservations (the post-drain / post-storm
        zero-leak assertion)."""
        with self._lock:
            assert len(set(self._free)) == len(self._free), "dup free ids"
            assert all(0 < p < self.pages for p in self._free)
            assert 0 <= self._reserved <= len(self._free), \
                f"reserved {self._reserved} > free {len(self._free)}"
            if expect_all_free:
                assert len(self._free) == self.usable_pages, \
                    (f"page leak: {self.usable_pages - len(self._free)} "
                     f"of {self.usable_pages} pages unaccounted for")
                assert self._reserved == 0, \
                    f"{self._reserved} pages still reserved"

    # -- device state -------------------------------------------------------

    def swap(self, k_flat, v_flat, k_scale=None, v_scale=None) -> None:
        """Rebind the pools to a program's donated outputs (scale pools
        included when this is a quantized pool)."""
        self.k_flat = k_flat
        self.v_flat = v_flat
        if self.scale_pages:
            if k_scale is None or v_scale is None:
                raise ValueError(
                    "quantized pool swap requires k_scale and v_scale")
            self.k_scale = k_scale
            self.v_scale = v_scale

    def utilization(self) -> float:
        with self._lock:
            return (self.usable_pages - len(self._free)) / \
                max(1, self.usable_pages)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            free = len(self._free)
            return {
                "pages": self.pages,
                "dtype": np.dtype(self.dtype).name,
                "scale_pages": self.scale_pages,
                "usable_pages": self.usable_pages,
                "free_pages": free,
                "used_pages": self.usable_pages - free,
                "reserved_pages": self._reserved,
                "utilization": (self.usable_pages - free) /
                max(1, self.usable_pages),
                **self.stats,
            }

    # -- observability ------------------------------------------------------

    def _gauges(self) -> None:
        """Occupancy gauges; inert while telemetry is off (registry must
        stay empty then — the record_dispatch contract)."""
        try:
            from ..observability.metrics import get_registry
            from ..observability.telemetry import get_telemetry
            if not get_telemetry().enabled:
                return
            with self._lock:
                free = len(self._free)
                reserved = self._reserved
            g = get_registry().gauge(
                "pt_serve_kv_pages",
                "Serve KV page-pool occupancy by state",
                labelnames=("state",))
            g.set(self.usable_pages - free, state="used")
            g.set(free, state="free")
            g.set(reserved, state="reserved")
            get_registry().gauge(
                "pt_serve_kv_utilization",
                "Fraction of usable KV pages in use").set(
                (self.usable_pages - free) / max(1, self.usable_pages))
        except Exception:
            pass

    def _register_memory_provider(self) -> None:
        try:
            from ..observability import memory as _memory
            mon = _memory.get_memory_monitor()
            if mon.enabled:
                mon.register_provider(self._memory_named)
        except Exception:
            pass

    def _memory_named(self):
        """Live-buffer attribution for the PR 14 census: the pools
        (and, for quantized pools, their scale shadows) under ``kv::``
        paths."""
        named = {"kv::k_pages": self.k_flat, "kv::v_pages": self.v_flat}
        if self.scale_pages:
            named["kv::k_scales"] = self.k_scale
            named["kv::v_scales"] = self.v_scale
        return named

    def null_padded_table(self, page_ids: Sequence[int],
                          max_pages: int) -> np.ndarray:
        """Host-side page table row: ids then null-page padding."""
        if len(page_ids) > max_pages:
            raise ValueError(
                f"{len(page_ids)} pages exceed table width {max_pages}")
        row = np.full((max_pages,), NULL_PAGE, np.int32)
        row[:len(page_ids)] = np.asarray(page_ids, np.int32)
        return row
