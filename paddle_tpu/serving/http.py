"""Stdlib HTTP front end for the serving engine.

Follows the :mod:`paddle_tpu.observability.server` shape (daemon
``ThreadingHTTPServer``, ephemeral ``port=0`` default, no socket bound
at import) and adds the serve surface:

 - ``GET  /healthz``      engine + scheduler health; **503 once the
                          zero-compile sentinel has tripped** (any
                          request-path compile) — the SLO alarm
 - ``GET  /metrics``      Prometheus exposition of the registry
 - ``POST /v1/generate``  ``{"tokens": [...], "max_new_tokens": N}`` →
                          ``{"tokens": [...], ...}``; 429 on
                          saturation, 400 on bad input
 - ``POST /v1/reload``    swap to the newest checkpoint generation
                          (zero-downtime weight swap); also runs on a
                          background poll when ``reload_interval`` is
                          set

Handler threads only ever submit numpy work to the scheduler and wait;
all device interaction happens on the scheduler's step loop.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from typing import Optional

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["ServeHTTPServer"]

_CTYPE_JSON = "application/json"
_CTYPE_METRICS = "text/plain; version=0.0.4; charset=utf-8"


class ServeHTTPServer:
    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 request_timeout: float = 120.0,
                 reload_interval: Optional[float] = None):
        self.engine = engine
        self._host = host
        self._requested_port = int(port)
        self._request_timeout = request_timeout
        self._reload_interval = reload_interval
        self._httpd = None
        self._thread = None
        self._reload_thread = None
        self._stop = threading.Event()
        self.port = None

    @property
    def host(self) -> str:
        return self._host

    def start(self) -> "ServeHTTPServer":
        """Bind + serve on daemon threads; starts the scheduler loop.
        Idempotent."""
        if self._httpd is not None:
            return self
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        engine = self.engine
        timeout = self._request_timeout
        engine.scheduler.start()

        class _Handler(BaseHTTPRequestHandler):
            def _send(self, code, ctype, body):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code, obj):
                self._send(code, _CTYPE_JSON,
                           (json.dumps(obj) + "\n").encode())

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        from ..observability.metrics import get_registry
                        self._send(200, _CTYPE_METRICS,
                                   get_registry().prometheus_text()
                                   .encode("utf-8"))
                    elif path == "/healthz":
                        health = engine.healthz()
                        self._send_json(200 if health.get("ok") else 503,
                                        health)
                    else:
                        self._send(404, "text/plain; charset=utf-8",
                                   b"not found; try /healthz /metrics "
                                   b"/v1/generate\n")
                except Exception as e:
                    logger.warning("serve endpoint error on %s: %s",
                                   path, e)
                    try:
                        self._send_json(500, {"error": str(e)})
                    except OSError:
                        pass

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(n) if n else b"{}"
                    if path == "/v1/generate":
                        self._generate(raw)
                    elif path == "/v1/reload":
                        step = engine.maybe_reload()
                        self._send_json(200, {
                            "reloaded": step is not None,
                            "weights_step": engine.weights_step})
                    else:
                        self._send_json(404, {"error": "unknown route"})
                except Exception as e:
                    logger.warning("serve endpoint error on %s: %s",
                                   path, e)
                    try:
                        self._send_json(500, {"error": str(e)})
                    except OSError:
                        pass

            def _generate(self, raw):
                from .scheduler import EngineSaturated
                t0 = time.monotonic()
                try:
                    body = json.loads(raw.decode("utf-8"))
                    tokens = body["tokens"]
                    max_new = body.get("max_new_tokens")
                except (ValueError, KeyError, TypeError) as e:
                    self._send_json(400, {"error": f"bad request: {e}"})
                    return
                try:
                    stream = engine.scheduler.submit(
                        tokens, max_new_tokens=max_new)
                except EngineSaturated as e:
                    self._send_json(429, {"error": str(e)})
                    return
                except ValueError as e:
                    self._send_json(400, {"error": str(e)})
                    return
                try:
                    out = stream.result(timeout=timeout)
                except TimeoutError as e:
                    self._send_json(504, {"error": str(e)})
                    return
                wall = time.monotonic() - t0
                _book_http_latency(wall)
                self._send_json(200, {
                    "tokens": [int(t) for t in out],
                    "request_id": stream.request_id,
                    "latency_ms": wall * 1e3,
                    "weights_step": engine.weights_step,
                })

            def log_message(self, fmt, *args):
                logger.debug("serve-http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pt-serve-http",
            daemon=True)
        self._thread.start()
        if self._reload_interval:
            self._stop.clear()
            self._reload_thread = threading.Thread(
                target=self._reload_loop, name="pt-serve-reload",
                daemon=True)
            self._reload_thread.start()
        logger.info("serve endpoint on http://%s:%d (/v1/generate, "
                    "/healthz, /metrics)", self._host, self.port)
        return self

    def _reload_loop(self):
        """Poll the checkpoint root and hot-swap newer generations —
        serving N while loading N+1."""
        while not self._stop.wait(self._reload_interval):
            try:
                step = self.engine.maybe_reload()
                if step is not None:
                    logger.info("background weight swap -> step %s", step)
            except Exception:
                logger.exception("background weight reload failed")

    def stop(self):
        self._stop.set()
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._reload_thread is not None:
            self._reload_thread.join(timeout=5.0)
            self._reload_thread = None
        self.engine.scheduler.stop()
        self.port = None


def _book_http_latency(seconds: float) -> None:
    """HTTP-level wall latency (includes queueing); inert while
    telemetry is off."""
    try:
        from ..observability.metrics import get_registry
        from ..observability.telemetry import get_telemetry
        if not get_telemetry().enabled:
            return
        get_registry().histogram(
            "pt_serve_http_request_seconds",
            "Wall time of /v1/generate requests").observe(seconds)
    except Exception:
        pass
