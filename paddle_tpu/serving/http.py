"""Stdlib HTTP front end for the serving engine.

Follows the :mod:`paddle_tpu.observability.server` shape (daemon
``ThreadingHTTPServer``, ephemeral ``port=0`` default, no socket bound
at import) and adds the serve surface:

 - ``GET  /healthz``      engine + scheduler health; **503 once the
                          zero-compile sentinel has tripped** (any
                          request-path compile), the hang watchdog
                          fired, or the engine is draining — the SLO
                          alarm
 - ``GET  /metrics``      Prometheus exposition of the registry
 - ``POST /v1/generate``  ``{"tokens": [...], "max_new_tokens": N,
                          "deadline_ms": D}`` → ``{"tokens": [...]}``;
                          429 + ``Retry-After`` on saturation/shed,
                          503 while draining, 504 on a missed
                          deadline/timeout, 499 when the request was
                          cancelled, 400 on bad input
 - ``POST /v1/cancel``    ``{"request_id": N}`` → evicts the request
                          at the next step boundary (pages released)
 - ``POST /v1/reload``    swap to the newest checkpoint generation
                          (zero-downtime weight swap); also runs on a
                          background poll when ``reload_interval`` is
                          set

Handler threads only ever submit numpy work to the scheduler and wait;
all device interaction happens on the scheduler's step loop.  While
waiting they watch the client socket: a disconnected caller's request
is cancelled (``cause="disconnect"``) instead of decoding for nobody.

SIGTERM lifecycle (:func:`install_drain_handler`): stop admission,
finish in-flight decodes within the drain budget, cancel the rest,
flush a flight dump, exit **143** — no partial responses, no leaked
pages on relaunch.
"""
from __future__ import annotations

import json
import logging
import os
import select
import socket
import threading
import time
from typing import Optional

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["ServeHTTPServer", "install_drain_handler", "DRAIN_EXIT_CODE"]

_CTYPE_JSON = "application/json"
_CTYPE_METRICS = "text/plain; version=0.0.4; charset=utf-8"

# 128 + SIGTERM: the exit status a supervisor reads as "asked to stop,
# stopped cleanly" after a graceful drain (canonical taxonomy:
# distributed/exit_codes.py)
from ..distributed.exit_codes import EXIT_DRAIN as DRAIN_EXIT_CODE  # noqa: E402


def _client_gone(sock) -> bool:
    """True when the peer has closed its end (EOF readable) — the
    waiting handler should cancel the request rather than decode for a
    caller that left."""
    try:
        r, _, _ = select.select([sock], [], [], 0)
        if not r:
            return False
        return sock.recv(1, socket.MSG_PEEK) == b""
    except (OSError, ValueError):
        return True


class ServeHTTPServer:
    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 request_timeout: float = 120.0,
                 reload_interval: Optional[float] = None):
        self.engine = engine
        self._host = host
        self._requested_port = int(port)
        self._request_timeout = request_timeout
        self._reload_interval = reload_interval
        self._httpd = None
        self._thread = None
        self._reload_thread = None
        self._stop = threading.Event()
        self.port = None

    @property
    def host(self) -> str:
        return self._host

    def start(self) -> "ServeHTTPServer":
        """Bind + serve on daemon threads; starts the scheduler loop.
        Idempotent."""
        if self._httpd is not None:
            return self
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        engine = self.engine
        timeout = self._request_timeout
        engine.scheduler.start()

        class _Handler(BaseHTTPRequestHandler):
            def _send(self, code, ctype, body, headers=()):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code, obj, headers=()):
                self._send(code, _CTYPE_JSON,
                           (json.dumps(obj) + "\n").encode(), headers)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        from ..observability.metrics import get_registry
                        self._send(200, _CTYPE_METRICS,
                                   get_registry().prometheus_text()
                                   .encode("utf-8"))
                    elif path == "/healthz":
                        health = engine.healthz()
                        self._send_json(200 if health.get("ok") else 503,
                                        health)
                    else:
                        self._send(404, "text/plain; charset=utf-8",
                                   b"not found; try /healthz /metrics "
                                   b"/v1/generate\n")
                except Exception as e:
                    logger.warning("serve endpoint error on %s: %s",
                                   path, e)
                    try:
                        self._send_json(500, {"error": str(e)})
                    except OSError:
                        pass

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(n) if n else b"{}"
                    if path == "/v1/generate":
                        self._generate(raw)
                    elif path == "/v1/cancel":
                        self._cancel(raw)
                    elif path == "/v1/reload":
                        step = engine.maybe_reload()
                        self._send_json(200, {
                            "reloaded": step is not None,
                            "weights_step": engine.weights_step})
                    else:
                        self._send_json(404, {"error": "unknown route"})
                except Exception as e:
                    logger.warning("serve endpoint error on %s: %s",
                                   path, e)
                    try:
                        self._send_json(500, {"error": str(e)})
                    except OSError:
                        pass

            def _cancel(self, raw):
                try:
                    body = json.loads(raw.decode("utf-8"))
                    rid = int(body["request_id"])
                except (ValueError, KeyError, TypeError) as e:
                    self._send_json(400, {"error": f"bad request: {e}"})
                    return
                ok = engine.scheduler.cancel(rid, cause="client")
                self._send_json(200, {"request_id": rid,
                                      "cancelled": bool(ok)})

            def _generate(self, raw):
                from .scheduler import (DeadlineExceeded, EngineSaturated,
                                        RequestCancelled, RequestShed)
                t0 = time.monotonic()
                try:
                    body = json.loads(raw.decode("utf-8"))
                    tokens = body["tokens"]
                    max_new = body.get("max_new_tokens")
                    deadline_ms = body.get("deadline_ms")
                except (ValueError, KeyError, TypeError) as e:
                    self._send_json(400, {"error": f"bad request: {e}"})
                    return
                try:
                    stream = engine.scheduler.submit(
                        tokens, max_new_tokens=max_new,
                        deadline_ms=deadline_ms)
                except RequestShed as e:
                    if e.reason == "draining":
                        self._send_json(503, {"error": str(e),
                                              "reason": e.reason})
                    else:
                        retry = max(1, int(float(e.retry_after or 1)
                                           + 0.999))
                        self._send_json(
                            429, {"error": str(e), "reason": e.reason},
                            headers=(("Retry-After", str(retry)),))
                    return
                except EngineSaturated as e:
                    self._send_json(429, {"error": str(e)},
                                    headers=(("Retry-After", "1"),))
                    return
                except ValueError as e:
                    self._send_json(400, {"error": str(e)})
                    return
                # wait, watching the wall clock AND the client socket:
                # an abandoned request is cancelled, never left decoding
                wall_deadline = t0 + timeout
                while not stream._done.wait(0.05):
                    if time.monotonic() >= wall_deadline:
                        stream.cancel(cause="timeout")
                        self._send_json(504, {
                            "error": f"request {stream.request_id} did "
                                     f"not finish in {timeout}s",
                            "request_id": stream.request_id})
                        return
                    if _client_gone(self.connection):
                        engine.scheduler.cancel(stream.request_id,
                                                cause="disconnect")
                        return  # nobody is listening
                err = stream._error
                if err is None:
                    wall = time.monotonic() - t0
                    _book_http_latency(wall)
                    self._send_json(200, {
                        "tokens": [int(t) for t in stream.tokens],
                        "request_id": stream.request_id,
                        "latency_ms": wall * 1e3,
                        "weights_step": engine.weights_step,
                    })
                elif isinstance(err, DeadlineExceeded):
                    self._send_json(504, {"error": str(err),
                                          "reason": "deadline",
                                          "request_id": stream.request_id})
                elif isinstance(err, RequestCancelled):
                    # nginx-style 499 "client closed request" for client
                    # cancels; 503 when the drain cut the request short
                    code = 503 if err.cause == "drain" else 499
                    self._send_json(code, {"error": str(err),
                                           "cause": err.cause,
                                           "request_id": stream.request_id})
                else:
                    self._send_json(500, {"error": str(err),
                                          "request_id": stream.request_id})

            def log_message(self, fmt, *args):
                logger.debug("serve-http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pt-serve-http",
            daemon=True)
        self._thread.start()
        if self._reload_interval:
            self._stop.clear()
            self._reload_thread = threading.Thread(
                target=self._reload_loop, name="pt-serve-reload",
                daemon=True)
            self._reload_thread.start()
        logger.info("serve endpoint on http://%s:%d (/v1/generate, "
                    "/v1/cancel, /healthz, /metrics)",
                    self._host, self.port)
        return self

    def _reload_loop(self):
        """Poll the checkpoint root and hot-swap newer generations —
        serving N while loading N+1."""
        while not self._stop.wait(self._reload_interval):
            try:
                step = self.engine.maybe_reload()
                if step is not None:
                    logger.info("background weight swap -> step %s", step)
            except Exception:
                logger.exception("background weight reload failed")

    def drain(self, budget_s: Optional[float] = None,
              settle_s: float = 1.0) -> bool:
        """Graceful-drain lifecycle: close admission (healthz degrades),
        finish in-flight decodes within the budget, cancel the rest,
        give handler threads a moment to flush their responses, book a
        flight dump, and stop.  Returns True when every in-flight
        request completed inside the budget."""
        clean = self.engine.scheduler.drain_gracefully(budget_s)
        # the scheduler resolved every stream; handler threads still
        # need a beat to write the queued responses before shutdown
        time.sleep(max(0.0, settle_s))
        try:
            from ..observability.trace import get_tracer
            get_tracer().flight_dump(reason="serve-drain clean=%s" % clean)
        except Exception:
            pass
        self.stop()
        return clean

    def stop(self):
        self._stop.set()
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._reload_thread is not None:
            self._reload_thread.join(timeout=5.0)
            self._reload_thread = None
        self.engine.scheduler.stop()
        self.port = None


def install_drain_handler(server: ServeHTTPServer, *,
                          budget_s: Optional[float] = None,
                          exit_code: int = DRAIN_EXIT_CODE):
    """SIGTERM → graceful drain → ``exit(143)``.

    Call from the main thread (signal module requirement).  The handler
    only sets a flag and hands off to a drain thread — nothing
    drain-sized runs in signal context.  Metrics stay scrapeable and
    ``/healthz`` reports 503 ``draining`` for the whole window, so a
    load balancer watching health stops routing before the listener
    goes away."""
    import signal

    fired = threading.Event()

    def _drain_and_exit():
        try:
            server.drain(budget_s)
        except Exception:
            logger.exception("graceful drain failed; exiting anyway")
        finally:
            os._exit(exit_code)

    def _on_term(signum, frame):
        if fired.is_set():  # second SIGTERM: stop waiting, just go
            os._exit(exit_code)
        fired.set()
        logger.info("SIGTERM: starting graceful drain (budget %s)",
                    budget_s if budget_s is not None else "config")
        threading.Thread(target=_drain_and_exit, name="pt-serve-drain",
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _on_term)


def _book_http_latency(seconds: float) -> None:
    """HTTP-level wall latency (includes queueing); inert while
    telemetry is off."""
    try:
        from ..observability.metrics import get_registry
        from ..observability.telemetry import get_telemetry
        if not get_telemetry().enabled:
            return
        get_registry().histogram(
            "pt_serve_http_request_seconds",
            "Wall time of /v1/generate requests").observe(seconds)
    except Exception:
        pass
