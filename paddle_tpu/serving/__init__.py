"""AOT-compiled serving engine (the `paddle/fluid/inference` parity
tentpole): per-bucket zero-compile serve graphs, paged KV-cache with
buffer donation, continuous batching, stdlib HTTP front end.

Quick start::

    from paddle_tpu.serving import (ModelSpec, ServeConfig, ServingEngine,
                                    init_params, save_served_model,
                                    load_engine)

    spec = ModelSpec(vocab_size=512, hidden=64, layers=2, heads=4)
    engine = ServingEngine(spec, init_params(spec), ServeConfig.from_env())
    tokens = engine.generate([[5, 9, 2]], max_new_tokens=8)[0]

    # or serve a directory over HTTP:
    save_served_model("/tmp/m", spec, init_params(spec))
    from paddle_tpu.serving.http import ServeHTTPServer
    ServeHTTPServer(load_engine("/tmp/m")).start()

Module map: :mod:`.model` (pure serve-side decoder fns over paged KV),
:mod:`.kv_cache` (block-pool page allocator + admission reservations),
:mod:`.engine` (AOT program ladder, compile sentinel, weight swap),
:mod:`.scheduler` (continuous batching), :mod:`.http` (front end).
"""
from .model import ModelSpec, init_params, prefill_step, decode_step
from .kv_cache import PagePool, KVPoolExhausted, NULL_PAGE
from .engine import (ServeConfig, ServingEngine, save_served_model,
                     load_engine, is_served_model_dir, SERVE_CONFIG_NAME)
from .scheduler import (ContinuousScheduler, GenerationStream,
                        EngineSaturated, RequestShed, RequestCancelled,
                        DeadlineExceeded, WATCHDOG_EXIT_CODE)

__all__ = [
    "ModelSpec", "init_params", "prefill_step", "decode_step",
    "PagePool", "KVPoolExhausted", "NULL_PAGE",
    "ServeConfig", "ServingEngine", "save_served_model", "load_engine",
    "is_served_model_dir", "SERVE_CONFIG_NAME",
    "ContinuousScheduler", "GenerationStream", "EngineSaturated",
    "RequestShed", "RequestCancelled", "DeadlineExceeded",
    "WATCHDOG_EXIT_CODE",
]
