"""AOT-compiled serving engine: per-bucket zero-compile serve graphs.

At construction the engine lowers+compiles every program it will ever
run — one prefill executable per sequence bucket and one decode
executable per batch bucket — then executes each once (warmup) and
arms the **serve compile sentinel**: from that point, any compile
observed in the process books ``pt_serve_unexpected_compiles_total``
and flips ``/healthz`` to 503.  The PR 3 recompile sentinel thereby
becomes an SLO alarm: on a serving box, a compile IS an incident.

Request-path discipline that keeps the sentinel quiet (enforced by
tpu-lint TPU019): the scheduler/HTTP layers touch only numpy and the
pre-compiled executables.  Even a stray ``jnp.asarray`` on the request
path would book a tiny convert/copy compile.

KV state is donated: each executable takes the pool arrays, writes the
step's K/V in place (XLA aliases the buffers — the PR 7 capture
convention), and the engine rebinds the pool to the returned arrays.

Zero-downtime weight swap: with a ``CheckpointManager`` attached,
:meth:`ServingEngine.maybe_reload` hot-swaps to generation N+1 between
steps while requests keep flowing — same program executables, new
param buffers (no recompile: shapes are the signature, not values).
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import warnings
from dataclasses import dataclass, asdict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .kv_cache import PagePool, NULL_PAGE, kv_page_budget
from .model import ModelSpec, init_params, prefill_step, decode_step

PRECISIONS = ("fp32", "bf16", "int8")

logger = logging.getLogger("paddle_tpu.serving")

__all__ = ["ServeConfig", "ServingEngine", "save_served_model",
           "load_engine", "SERVE_CONFIG_NAME"]

SERVE_CONFIG_NAME = "serve_config.json"

# CPU/interpret runs can't honor every donation; the engine's rebind
# protocol is correct either way (the capture-layer convention)
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

# >0 while ANY engine in the process is inside its sanctioned AOT
# build; armed sentinels ignore those compiles (a second engine coming
# up — blue/green, tests — is not a request-path incident)
_AOT_BUILD_DEPTH = 0
_AOT_BUILD_LOCK = threading.Lock()


@contextlib.contextmanager
def aot_build_phase():
    """Mark the enclosed work as a sanctioned (non-request-path) compile
    phase.  Engine construction uses it, and so does the PTQ tooling
    (``serving/quant.py``) whose eager calibration/quality replays must
    not book ``pt_serve_unexpected_compiles_total`` on a live engine."""
    global _AOT_BUILD_DEPTH
    with _AOT_BUILD_LOCK:
        _AOT_BUILD_DEPTH += 1
    try:
        yield
    finally:
        with _AOT_BUILD_LOCK:
            _AOT_BUILD_DEPTH -= 1


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v else default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


def _env_buckets(name: str, default: Tuple[int, ...]) -> Tuple[int, ...]:
    v = os.environ.get(name)
    if not v:
        return tuple(default)
    return tuple(int(x) for x in v.replace(";", ",").split(",") if x.strip())


@dataclass(frozen=True)
class ServeConfig:
    """Engine shape/capacity configuration.

    Every field has an env override (read by :meth:`from_env`) so a
    deployment can retune the ladder without touching the served model
    dir:

      PT_SERVE_BUCKETS          decode batch ladder, e.g. "2,4,8,16"
      PT_SERVE_PREFILL_BUCKETS  prompt seq ladder, e.g. "16,32,64"
      PT_SERVE_KV_PAGES         total pool pages (incl. null page)
      PT_SERVE_PAGE_SIZE        tokens per page
      PT_SERVE_MAX_INFLIGHT     admission cap (queued + active)
      PT_SERVE_DEADLINE_MS      server-default request deadline (0 = none)
      PT_SERVE_MAX_QUEUE        bounded admission queue (0 = unbounded)
      PT_SERVE_DRAIN_S          graceful-drain budget on SIGTERM
      PT_SERVE_PRECISION        serve numerics: fp32 | bf16 | int8

    ``kv_pages`` is denominated in fp32 pages (a byte budget): lower
    precisions scale the physical page count up at pool construction
    (:func:`.kv_cache.kv_page_budget`), which is where the int8 mode's
    ~2x+ admission headroom comes from.
    """

    decode_buckets: Tuple[int, ...] = (2, 4, 8, 16)
    prefill_buckets: Tuple[int, ...] = (16, 32, 64)
    kv_pages: int = 128
    page_size: int = 16
    max_inflight: int = 64
    max_new_tokens: int = 32
    eos_id: int = -1          # <0: never stops early (length-bounded)
    deadline_ms: float = 0.0  # server default; 0 = no deadline
    max_queue: int = 256      # bounded queue; 0 = unbounded
    drain_s: float = 10.0     # SIGTERM drain budget (seconds)
    precision: str = "fp32"   # fp32 | bf16 | int8

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        base = cls(
            decode_buckets=_env_buckets(
                "PT_SERVE_BUCKETS", cls.decode_buckets),
            prefill_buckets=_env_buckets(
                "PT_SERVE_PREFILL_BUCKETS", cls.prefill_buckets),
            kv_pages=_env_int("PT_SERVE_KV_PAGES", cls.kv_pages),
            page_size=_env_int("PT_SERVE_PAGE_SIZE", cls.page_size),
            max_inflight=_env_int("PT_SERVE_MAX_INFLIGHT",
                                  cls.max_inflight),
            max_new_tokens=_env_int("PT_SERVE_MAX_NEW_TOKENS",
                                    cls.max_new_tokens),
            eos_id=_env_int("PT_SERVE_EOS_ID", cls.eos_id),
            deadline_ms=_env_float("PT_SERVE_DEADLINE_MS",
                                   cls.deadline_ms),
            max_queue=_env_int("PT_SERVE_MAX_QUEUE", cls.max_queue),
            drain_s=_env_float("PT_SERVE_DRAIN_S", cls.drain_s),
            precision=os.environ.get("PT_SERVE_PRECISION") or cls.precision,
        )
        return base.replace(**overrides) if overrides else base

    def replace(self, **kw) -> "ServeConfig":
        d = asdict(self)
        d.update(kw)
        d["decode_buckets"] = tuple(d["decode_buckets"])
        d["prefill_buckets"] = tuple(d["prefill_buckets"])
        return ServeConfig(**d)

    def to_dict(self) -> Dict[str, Any]:
        d = asdict(self)
        d["decode_buckets"] = list(self.decode_buckets)
        d["prefill_buckets"] = list(self.prefill_buckets)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeConfig":
        names = set(cls.__dataclass_fields__)
        kw = {k: v for k, v in d.items() if k in names}
        for key in ("decode_buckets", "prefill_buckets"):
            if key in kw:
                kw[key] = tuple(int(x) for x in kw[key])
        return cls(**kw)

    def normalized(self, spec: ModelSpec) -> "ServeConfig":
        """Clamp the ladders to what the model/pool can serve.

        Decode buckets are clamped to >= 2: XLA's batch-1 gemv path
        has a different reduction order, and bit-identical decode
        across batch compositions (the continuous-batching contract)
        only holds for matmul-shaped batches.  A solo sequence decodes
        in a 2-bucket with a null padding row instead.
        """
        dec = sorted({max(2, int(b)) for b in self.decode_buckets})
        pre = sorted({int(s) for s in self.prefill_buckets
                      if int(s) <= spec.max_seq_len})
        if not pre:
            pre = [spec.max_seq_len]
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")
        if self.precision not in PRECISIONS:
            raise ValueError(
                f"precision {self.precision!r} not in {PRECISIONS}")
        return self.replace(decode_buckets=tuple(dec),
                            prefill_buckets=tuple(pre))


def _struct_like(tree):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


def _to_serve_device(tree):
    # pin to ONE device: the executables are compiled against
    # SingleDeviceSharding, but checkpoint restores (and callers running
    # under a distributed mesh) may hand us NamedSharded arrays
    dev = jax.local_devices()[0]
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, dev), tree)


class ServingEngine:
    """Programs + paged KV pool + hot-swappable weights.

    The request path (scheduler / HTTP) calls :meth:`prefill` and
    :meth:`decode`, which only ever touch numpy and the AOT-compiled
    executables built in ``_build_programs``.
    """

    def __init__(self, spec: ModelSpec, params, config: ServeConfig = None,
                 checkpoint_manager=None, weights_step: Optional[int] = None):
        self.spec = spec
        self.config = (config or ServeConfig.from_env()).normalized(spec)
        self.checkpoint_manager = checkpoint_manager
        self.max_pages_per_seq = -(-spec.max_seq_len // self.config.page_size)
        # the whole construction is a sanctioned build phase: pool
        # creation (jnp.zeros fill) and warmup compile too, and must not
        # trip an already-armed sentinel on another live engine
        with aot_build_phase():
            prec = self.config.precision
            kv_dtype = {"fp32": jnp.float32, "bf16": jnp.bfloat16,
                        "int8": jnp.int8}[prec]
            # the configured kv_pages is an fp32 byte budget — lower
            # precisions buy more physical pages for the same spend,
            # which is the admission-headroom win the bench measures
            self.pool = PagePool(
                layers=spec.layers,
                pages=kv_page_budget(self.config.kv_pages, prec,
                                     spec.head_dim),
                page_size=self.config.page_size, heads=spec.heads,
                head_dim=spec.head_dim, dtype=kv_dtype,
                scale_pages=(prec == "int8"))
            self._params = _to_serve_device(self._prepare_params(params))
            self._weights_step = weights_step
            self._weights_lock = threading.Lock()
            self.unexpected_compiles = 0
            self._warmed = False
            self._prefill_exe: Dict[int, Any] = {}
            self._decode_exe: Dict[int, Any] = {}
            self.compiled_programs = 0
            self._build_programs()
            self._warmup()
        self._arm_sentinel()
        from .scheduler import ContinuousScheduler
        self.scheduler = ContinuousScheduler(self)

    def _prepare_params(self, params):
        """Convert an incoming weight tree to the engine's precision.

        int8: deterministic inline PTQ (same weights always quantize to
        the same bytes, so an fp32 dir served under
        ``PT_SERVE_PRECISION=int8`` matches a saved quantized dir bit
        for bit); already-quantized trees pass through.  bf16: cast
        every float leaf.  fp32: identity.
        """
        prec = self.config.precision
        if prec == "int8":
            from . import quant as _quant
            if not _quant.is_quantized_params(params):
                params = _quant.quantize_params(params, self.spec)
            return params
        if prec == "bf16":
            return jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                else a, dict(params))
        return params

    # -- AOT build (the only place that is ALLOWED to compile) --------------

    def _build_programs(self) -> None:
        """Lower+compile the full program ladder ahead of time."""
        with aot_build_phase():
            self._build_programs_inner()

    def _build_programs_inner(self) -> None:
        spec, cfg = self.spec, self.config
        ps = cfg.page_size
        int8 = cfg.precision == "int8"
        p_struct = _struct_like(self._params)
        k_struct = _struct_like(self.pool.k_flat)
        s_struct = _struct_like(self.pool.k_scale) if int8 else None
        i32 = np.int32
        # fp32 keeps its PR 15 program names so audit/bench baselines
        # stay comparable; other precisions are distinct programs
        sfx = "" if cfg.precision == "fp32" else f"_{cfg.precision}"

        if int8:
            # the scale pools are donated state exactly like the value
            # pools — the step rewrites both and the engine rebinds all
            # four (donate_argnums covers 1..4)
            def _pf(params, k_flat, v_flat, k_scale, v_scale, tokens,
                    length, page_table):
                return prefill_step(spec, params, k_flat, v_flat, tokens,
                                    length, page_table, page_size=ps,
                                    k_scale=k_scale, v_scale=v_scale)

            def _dec(params, k_flat, v_flat, k_scale, v_scale, tokens,
                     positions, page_tables):
                return decode_step(spec, params, k_flat, v_flat, tokens,
                                   positions, page_tables, page_size=ps,
                                   k_scale=k_scale, v_scale=v_scale)

            donate = (1, 2, 3, 4)
            labels = ("params", "k_flat", "v_flat", "k_scale", "v_scale",
                      "tokens", "positions", "page_tables")
            kv_args = (k_struct, k_struct, s_struct, s_struct)
        else:
            def _pf(params, k_flat, v_flat, tokens, length, page_table):
                return prefill_step(spec, params, k_flat, v_flat, tokens,
                                    length, page_table, page_size=ps)

            def _dec(params, k_flat, v_flat, tokens, positions,
                     page_tables):
                return decode_step(spec, params, k_flat, v_flat, tokens,
                                   positions, page_tables, page_size=ps)

            donate = (1, 2)
            labels = ("params", "k_flat", "v_flat", "tokens",
                      "positions", "page_tables")
            kv_args = (k_struct, k_struct)

        pf_jit = jax.jit(_pf, donate_argnums=donate)
        dec_jit = jax.jit(_dec, donate_argnums=donate)

        # graph audit (tools/audit): when enabled, every bucket
        # program's traced jaxpr is audited during the build — load
        # time only, sharing the trace the AOT lower needs anyway.
        # The donation layout handed over mirrors donate_argnums.
        aud = None
        from ..tools.audit import runtime as _audit_rt
        if _audit_rt.audit_enabled():
            aud = _audit_rt
            n_p = len(jax.tree_util.tree_leaves(p_struct))
            n_kv = len(kv_args) * len(jax.tree_util.tree_leaves(k_struct))

        def _compile(jitted, name, *args):
            if aud is None:
                exe = jitted.lower(*args).compile()
            else:
                traced = jitted.trace(*args)
                aud.audit_serve_trace(name, traced.jaxpr, n_p, n_kv,
                                      args, labels=labels)
                exe = traced.lower().compile()
            self._account_compile(name)
            return exe

        for s in cfg.prefill_buckets:
            self._prefill_exe[s] = _compile(
                pf_jit, f"serve_prefill_s{s}{sfx}",
                p_struct, *kv_args,
                jax.ShapeDtypeStruct((s,), i32),
                jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((self.max_pages_per_seq,), i32))

        for b in cfg.decode_buckets:
            self._decode_exe[b] = _compile(
                dec_jit, f"serve_decode_b{b}{sfx}",
                p_struct, *kv_args,
                jax.ShapeDtypeStruct((b,), i32),
                jax.ShapeDtypeStruct((b,), i32),
                jax.ShapeDtypeStruct((b, self.max_pages_per_seq), i32))

        self.compiled_programs = len(self._prefill_exe) + len(self._decode_exe)
        logger.info(
            "serve programs compiled: %d prefill buckets %s, %d decode "
            "buckets %s", len(self._prefill_exe),
            list(cfg.prefill_buckets), len(self._decode_exe),
            list(cfg.decode_buckets))

    def _account_compile(self, name: str) -> None:
        """Book load-time compiles on the standard compile feed (only
        when the log watcher isn't already counting them — the capture
        layer convention)."""
        try:
            from ..observability.telemetry import get_telemetry
            tel = get_telemetry()
            if not tel._watcher.installed:
                tel.record_compile(name, signature="aot-build")
        except Exception:
            pass

    def _kv_state(self):
        """The donated pool arrays in program argument order (value
        pools, plus scale pools on a quantized engine)."""
        if self.pool.scale_pages:
            return (self.pool.k_flat, self.pool.v_flat,
                    self.pool.k_scale, self.pool.v_scale)
        return (self.pool.k_flat, self.pool.v_flat)

    def _warmup(self) -> None:
        """Execute every program once so first-request latency pays no
        lazy initialization, and the sentinel can be armed on a
        provably quiet path.  Warmup traffic writes only the null page."""
        maxp = self.max_pages_per_seq
        for s, exe in self._prefill_exe.items():
            *state, _, _ = exe(self._params, *self._kv_state(),
                               np.zeros((s,), np.int32), np.int32(1),
                               np.zeros((maxp,), np.int32))
            self.pool.swap(*state)
        for b, exe in self._decode_exe.items():
            *state, _, _ = exe(self._params, *self._kv_state(),
                               np.zeros((b,), np.int32),
                               np.zeros((b,), np.int32),
                               np.zeros((b, maxp), np.int32))
            self.pool.swap(*state)
        jax.block_until_ready(self.pool.k_flat)

    def _arm_sentinel(self) -> None:
        """After this point, ANY observed compile is a request-path
        compile: book it and trip health."""
        try:
            from ..observability.telemetry import get_telemetry
            tel = get_telemetry()
            tel.ensure_compile_watch()
            tel.add_compile_listener(self._on_compile_event)
        except Exception:
            logger.exception("serve compile sentinel not armed")
        self._warmed = True

    def _on_compile_event(self, name: str, signature: str = "") -> None:
        if not self._warmed or _AOT_BUILD_DEPTH > 0:
            return
        self.unexpected_compiles += 1
        logger.warning(
            "unexpected request-path compile: %s — the serve ladder "
            "should cover every shape; /healthz now degraded", name)
        try:
            from ..observability.metrics import get_registry
            from ..observability.telemetry import get_telemetry
            if get_telemetry().enabled:
                get_registry().counter(
                    "pt_serve_unexpected_compiles_total",
                    "Compiles observed after serve warmup (SLO alarm)",
                    labelnames=("fn",)).inc(fn=name)
        except Exception:
            pass

    def close(self) -> None:
        try:
            from ..observability.telemetry import get_telemetry
            get_telemetry().remove_compile_listener(self._on_compile_event)
        except Exception:
            pass

    # -- request path (numpy + compiled executables ONLY) -------------------

    def prefill_bucket_for(self, n: int) -> int:
        for s in self.config.prefill_buckets:
            if n <= s:
                return s
        raise ValueError(
            f"prompt length {n} exceeds largest prefill bucket "
            f"{self.config.prefill_buckets[-1]}")

    def decode_bucket_for(self, n: int) -> int:
        for b in self.config.decode_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"{n} active sequences exceed largest decode bucket "
            f"{self.config.decode_buckets[-1]}")

    def prefill(self, tokens: Sequence[int],
                page_table: np.ndarray) -> int:
        """Run one prompt; returns the first generated token."""
        n = len(tokens)
        s = self.prefill_bucket_for(n)
        padded = np.zeros((s,), np.int32)
        padded[:n] = np.asarray(tokens, np.int32)
        with self._weights_lock:
            params = self._params
        *state, nxt, _ = self._prefill_exe[s](
            params, *self._kv_state(),
            padded, np.int32(n), np.asarray(page_table, np.int32))
        self.pool.swap(*state)
        return int(nxt)

    def decode(self, tokens: np.ndarray, positions: np.ndarray,
               page_tables: np.ndarray) -> np.ndarray:
        """One decode step over ``n`` active rows, padded to a bucket.

        Padding rows carry position 0 + the all-null page table, so
        their (garbage) K/V writes land in the null page.
        """
        n = tokens.shape[0]
        b = self.decode_bucket_for(max(n, 1))
        maxp = self.max_pages_per_seq
        tok = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        pt = np.full((b, maxp), NULL_PAGE, np.int32)
        tok[:n] = tokens
        pos[:n] = positions
        pt[:n] = page_tables
        with self._weights_lock:
            params = self._params
        *state, nxt, _ = self._decode_exe[b](
            params, *self._kv_state(), tok, pos, pt)
        self.pool.swap(*state)
        return np.asarray(nxt)[:n]

    # -- weights ------------------------------------------------------------

    @property
    def weights_step(self) -> Optional[int]:
        return self._weights_step

    def install_weights(self, params, step: Optional[int] = None) -> None:
        """Hot-swap to a new weight generation between steps.

        Same treedef/shapes required — the executables' signature is
        structural, so matching weights swap with zero compiles.
        Incoming weights pass through the engine's precision conversion
        first (fp32 trees quantize/cast to match).
        """
        params = self._prepare_params(params)
        old = jax.tree_util.tree_structure(self._params)
        new = jax.tree_util.tree_structure(params)
        if old != new:
            raise ValueError("weight swap changes the parameter tree "
                             f"({new} vs {old})")
        for (_, a), (_, b) in zip(
                sorted(self._params.items()), sorted(params.items())):
            if a.shape != b.shape:
                raise ValueError(
                    f"weight swap changes a shape: {b.shape} vs {a.shape}")
        dev = _to_serve_device(params)
        with self._weights_lock:
            self._params = dev
            self._weights_step = step
        logger.info("weights swapped to generation step=%s", step)

    def maybe_reload(self) -> Optional[int]:
        """Swap in a newer checkpoint generation if one exists
        (zero-downtime: serving N while loading N+1)."""
        mgr = self.checkpoint_manager
        if mgr is None:
            return None
        latest = mgr.latest_step()
        if latest is None or latest == self._weights_step:
            return None
        state, step = mgr.restore_latest(template=self._params)
        if step is None:
            return None
        self.install_weights(state, step)
        return step

    # -- convenience / health ----------------------------------------------

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: Optional[int] = None) -> List[List[int]]:
        """Synchronous batch generate through the continuous-batching
        scheduler (submits all, drains the loop)."""
        streams = [self.scheduler.submit(p, max_new_tokens=max_new_tokens)
                   for p in prompts]
        self.scheduler.drain()
        # the drain above already emptied the loop; the bound is a
        # backstop so a wedged stream can never hang the caller forever
        return [st.result(timeout=300.0) for st in streams]

    def healthz(self) -> Dict[str, Any]:
        sched = getattr(self, "scheduler", None)
        draining = bool(sched is not None and sched.draining)
        hang = bool(sched is not None and sched.hang_detected)
        try:
            self.pool.check_consistency()
            kv_consistent = True
        except AssertionError:
            kv_consistent = False
        h = {
            # degraded while draining (LBs must stop routing here), on
            # any request-path compile, a tripped hang watchdog, or a
            # page-pool invariant violation
            "ok": (self.unexpected_compiles == 0 and not draining
                   and not hang and kv_consistent),
            "draining": draining,
            "hang_detected": hang,
            "kv_consistent": kv_consistent,
            "unexpected_compiles": self.unexpected_compiles,
            "compiled_programs": self.compiled_programs,
            "precision": self.config.precision,
            "decode_buckets": list(self.config.decode_buckets),
            "prefill_buckets": list(self.config.prefill_buckets),
            "weights_step": self._weights_step,
            "kv": self.pool.snapshot(),
        }
        if sched is not None:
            h.update(sched.snapshot())
        return h


# -- served-model directory format ------------------------------------------

def save_served_model(path: str, spec: ModelSpec, params,
                      config: Optional[ServeConfig] = None,
                      step: int = 0) -> str:
    """Write a self-describing served-model dir:
    ``serve_config.json`` (architecture + serve shapes) plus a
    CheckpointManager weight tree — the unit `Predictor` and
    :func:`load_engine` consume, and the unit the trainer republishes
    for zero-downtime swaps."""
    from ..distributed.checkpoint_manager import CheckpointManager
    os.makedirs(path, exist_ok=True)
    cfg = config or ServeConfig.from_env()
    with open(os.path.join(path, SERVE_CONFIG_NAME), "w") as f:
        json.dump({"model": spec.to_dict(), "serve": cfg.to_dict()},
                  f, indent=2, sort_keys=True)
    mgr = CheckpointManager(os.path.join(path, "weights"))
    mgr.save(step, dict(params), block=True)
    return path


def is_served_model_dir(path: str) -> bool:
    return os.path.isdir(path) and \
        os.path.exists(os.path.join(path, SERVE_CONFIG_NAME))


def load_engine(path: str, config: Optional[ServeConfig] = None,
                **config_overrides) -> ServingEngine:
    """Build a :class:`ServingEngine` from a served-model dir.

    Config precedence: explicit ``config`` arg > env overrides >
    ``serve_config.json`` on disk.
    """
    from ..distributed.checkpoint_manager import CheckpointManager
    with open(os.path.join(path, SERVE_CONFIG_NAME)) as f:
        meta = json.load(f)
    spec = ModelSpec.from_dict(meta.get("model", {}))
    if config is None:
        file_cfg = ServeConfig.from_dict(meta.get("serve", {}))
        env_kw = {}
        for fname, env in (
                ("decode_buckets", "PT_SERVE_BUCKETS"),
                ("prefill_buckets", "PT_SERVE_PREFILL_BUCKETS"),
                ("kv_pages", "PT_SERVE_KV_PAGES"),
                ("page_size", "PT_SERVE_PAGE_SIZE"),
                ("max_inflight", "PT_SERVE_MAX_INFLIGHT"),
                ("max_new_tokens", "PT_SERVE_MAX_NEW_TOKENS"),
                ("eos_id", "PT_SERVE_EOS_ID"),
                ("deadline_ms", "PT_SERVE_DEADLINE_MS"),
                ("max_queue", "PT_SERVE_MAX_QUEUE"),
                ("drain_s", "PT_SERVE_DRAIN_S"),
                ("precision", "PT_SERVE_PRECISION")):
            if os.environ.get(env):
                env_kw[fname] = getattr(ServeConfig.from_env(), fname)
        config = file_cfg.replace(**env_kw) if env_kw else file_cfg
    if config_overrides:
        config = config.replace(**config_overrides)
    mgr = CheckpointManager(os.path.join(path, "weights"))
    precision_meta = meta.get("precision") or {}
    with aot_build_phase():
        # template construction + checkpoint restore run jnp ops before
        # ServingEngine's own sanctioned phase opens — keep them from
        # booking compiles on other live engines in the process
        if precision_meta.get("mode") == "int8":
            # quantized dir: the restore template mirrors the quantized
            # tree (``::q``/``::scale`` + ``act::`` leaves) so treedef
            # validation still bites
            from .quant import quantized_template
            template = quantized_template(
                spec,
                act_sites=sorted(precision_meta.get("act_scales", {})))
        else:
            template = init_params(spec, seed=0)
        params, step = mgr.restore_latest(template=template)
    if step is None:
        raise FileNotFoundError(
            f"no valid weight checkpoint under {path}/weights")
    return ServingEngine(spec, params, config,
                         checkpoint_manager=mgr, weights_step=step)
