"""Viterbi decoding (ref: ``python/paddle/text/viterbi_decode.py``
ViterbiDecoder over the viterbi_decode op).

TPU-native: the DP over time steps is a ``lax.scan`` — one compiled kernel,
no per-step dispatch."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..ops.op_utils import nary

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """Args follow the reference: potentials [B, T, N] unary scores,
    transition_params [N, N] (or [N+2, N+2] with BOS/EOS tags when
    ``include_bos_eos_tag``), lengths [B]. Returns (scores [B],
    paths [B, T])."""

    def f(pot, trans, lens):
        B, T, N = pot.shape
        if include_bos_eos_tag:
            # rows/cols N..N+1 are BOS/EOS (reference convention: last two)
            bos, eos = N, N + 1
            start = trans[bos, :N][None, :] + pot[:, 0]
            stop_bonus = trans[:N, eos]
        else:
            start = pot[:, 0]
            stop_bonus = jnp.zeros(N, pot.dtype)
        tr = trans[:N, :N]

        def step(carry, xs):
            alpha, t = carry
            emit = xs  # [B, N]
            # scores[b, i, j] = alpha[b, i] + tr[i, j] + emit[b, j]
            scores = alpha[:, :, None] + tr[None, :, :]
            best_prev = jnp.argmax(scores, axis=1)           # [B, N]
            new_alpha = jnp.max(scores, axis=1) + emit       # [B, N]
            # inactive steps (t >= lens) carry alpha through
            active = (t < lens)[:, None]
            new_alpha = jnp.where(active, new_alpha, alpha)
            return (new_alpha, t + 1), (best_prev, active)

        (alpha, _), (backptrs, actives) = jax.lax.scan(
            step, (start, 1), jnp.swapaxes(pot[:, 1:], 0, 1))
        final = alpha + stop_bonus[None, :]
        scores = jnp.max(final, axis=1)
        last_tag = jnp.argmax(final, axis=1)  # [B]

        def backward(carry, xs):
            tag = carry
            bp, active = xs  # [B, N], [B, 1]
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            prev = jnp.where(active[:, 0], prev, tag)
            return prev, tag

        _, tags_rev = jax.lax.scan(backward, last_tag,
                                   (backptrs, actives), reverse=True)
        first_tag = _
        paths = jnp.concatenate([first_tag[None], tags_rev], axis=0)
        return scores, jnp.swapaxes(paths, 0, 1)  # [B], [B, T]

    if lengths is None:
        B, T = potentials.shape[0], potentials.shape[1]
        lengths = Tensor(jnp.full((B,), T, jnp.int32))
    return nary(f, [potentials, transition_params, lengths],
                name="viterbi_decode", n_out=2)


class ViterbiDecoder:
    """Layer-style wrapper (ref ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
