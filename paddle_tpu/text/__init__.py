"""``paddle.text`` (ref: ``python/paddle/text/``): viterbi decode + dataset
classes.

Dataset note: the reference datasets stream from Baidu mirrors
(``python/paddle/text/datasets/*.py`` DATA_URL). This framework is built
for air-gapped TPU pods, so each dataset accepts ``data_file`` (a local
copy, same format as the reference) and offers ``synthetic=True`` to
generate a deterministic synthetic split with the right schema for
pipeline tests — the pattern the reference's unit tests use for speed.
"""
from .viterbi import viterbi_decode, ViterbiDecoder  # noqa: F401
from . import datasets  # noqa: F401
from .datasets import (  # noqa: F401
    Imdb, Imikolov, UCIHousing, Conll05st, Movielens, WMT14, WMT16)

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets", "Imdb",
           "Imikolov", "UCIHousing", "Conll05st", "Movielens", "WMT14", "WMT16"]
