"""Text datasets (ref: ``python/paddle/text/datasets/``).

Each class matches the reference's item schema; data comes from a local
``data_file`` (same archive format the reference downloads) or, with
``synthetic=True``, a deterministic generated split for pipeline testing.
"""
from __future__ import annotations

import os
import tarfile

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Conll05st", "Movielens",
           "WMT14", "WMT16"]


class _SyntheticMixin:
    def _require(self, data_file, synthetic):
        if data_file and os.path.exists(data_file):
            return "file"
        if synthetic:
            return "synthetic"
        raise FileNotFoundError(
            f"{type(self).__name__}: pass data_file= (local copy of the "
            "reference dataset archive) or synthetic=True for a generated "
            "split (no network access on TPU pods)")


class Imdb(_SyntheticMixin, Dataset):
    """IMDB sentiment (ref ``datasets/imdb.py``): (ids[int64], label)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 synthetic=False, vocab_size=5000, n_samples=512,
                 max_len=64):
        src = self._require(data_file, synthetic)
        self.word_idx = {}
        self.docs, self.labels = [], []
        if src == "file":
            self._load_archive(data_file, mode, cutoff)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.word_idx = {f"w{i}": i for i in range(vocab_size)}
            for i in range(n_samples):
                ln = rng.randint(8, max_len)
                self.docs.append(rng.randint(0, vocab_size, ln,
                                             dtype=np.int64))
                self.labels.append(int(rng.randint(0, 2)))

    def _load_archive(self, data_file, mode, cutoff):
        import re
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        freq = {}
        texts = []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                match = pat.match(m.name)
                if not match:
                    continue
                words = tf.extractfile(m).read().decode(
                    "utf-8", "ignore").lower().split()
                texts.append((words, 1 if match.group(1) == "pos" else 0))
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
        vocab = [w for w, c in sorted(freq.items(), key=lambda kv: -kv[1])
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = len(self.word_idx)
        for words, lab in texts:
            self.docs.append(np.asarray(
                [self.word_idx.get(w, unk) for w in words], np.int64))
            self.labels.append(lab)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(_SyntheticMixin, Dataset):
    """PTB n-gram dataset (ref ``datasets/imikolov.py``)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, synthetic=False,
                 vocab_size=2000, n_samples=2048):
        src = self._require(data_file, synthetic)
        self.window_size = window_size
        self.samples = []
        if src == "synthetic":
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.word_idx = {f"w{i}": i for i in range(vocab_size)}
            for _ in range(n_samples):
                self.samples.append(rng.randint(0, vocab_size, window_size,
                                                dtype=np.int64))
        else:
            self._load_archive(data_file, mode, min_word_freq)

    def _load_archive(self, data_file, mode, min_word_freq):
        sub = "train" if mode == "train" else "valid"
        freq, sents = {}, []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if f"ptb.{sub}.txt" not in m.name:
                    continue
                for line in tf.extractfile(m).read().decode().splitlines():
                    words = ["<s>"] + line.strip().split() + ["<e>"]
                    sents.append(words)
                    for w in words:
                        freq[w] = freq.get(w, 0) + 1
        vocab = [w for w, c in freq.items() if c >= min_word_freq]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = len(self.word_idx)
        for words in sents:
            ids = [self.word_idx.get(w, unk) for w in words]
            for i in range(len(ids) - self.window_size + 1):
                self.samples.append(np.asarray(ids[i:i + self.window_size],
                                               np.int64))

    def __getitem__(self, idx):
        s = self.samples[idx]
        return tuple(s[:-1]), s[-1]

    def __len__(self):
        return len(self.samples)


class UCIHousing(_SyntheticMixin, Dataset):
    """Boston housing regression (ref ``datasets/uci_housing.py``):
    (features[13], price)."""

    def __init__(self, data_file=None, mode="train", synthetic=False,
                 n_samples=404):
        src = self._require(data_file, synthetic)
        if src == "file":
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            X = rng.randn(n_samples, 13).astype(np.float32)
            w = rng.randn(13).astype(np.float32)
            y = X @ w + 0.1 * rng.randn(n_samples).astype(np.float32)
            raw = np.concatenate([X, y[:, None]], axis=1)
        # normalize features (the reference does the same)
        feats = raw[:, :-1]
        feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-8)
        self.data = np.concatenate([feats, raw[:, -1:]], axis=1)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Conll05st(_SyntheticMixin, Dataset):
    """SRL dataset schema (ref ``datasets/conll05.py``): word/predicate/
    context ids + label sequence."""

    def __init__(self, data_file=None, mode="train", synthetic=False,
                 vocab_size=1000, n_labels=20, n_samples=256, max_len=32):
        self._require(None, synthetic)  # archive parsing not implemented
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.samples = []
        for _ in range(n_samples):
            ln = rng.randint(5, max_len)
            words = rng.randint(0, vocab_size, ln, dtype=np.int64)
            pred = rng.randint(0, vocab_size, ln, dtype=np.int64)
            labels = rng.randint(0, n_labels, ln, dtype=np.int64)
            self.samples.append((words, pred, labels))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Movielens(_SyntheticMixin, Dataset):
    """MovieLens ratings (ref ``datasets/movielens.py``):
    (user_id, gender, age, job, movie_id, category, title, rating)."""

    def __init__(self, data_file=None, mode="train", synthetic=False,
                 n_users=500, n_movies=800, n_samples=4096):
        self._require(data_file, synthetic)
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.samples = []
        for _ in range(n_samples):
            self.samples.append((
                np.int64(rng.randint(1, n_users)),
                np.int64(rng.randint(0, 2)),
                np.int64(rng.randint(0, 7)),
                np.int64(rng.randint(0, 21)),
                np.int64(rng.randint(1, n_movies)),
                rng.randint(0, 18, 3).astype(np.int64),
                rng.randint(0, 5000, 8).astype(np.int64),
                np.float32(rng.randint(1, 6)),
            ))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT14(_SyntheticMixin, Dataset):
    """WMT14 en-fr translation (ref ``datasets/wmt14.py``): items are
    (src_ids, trg_ids, trg_ids_next) int64 arrays; ids 0/1/2 are
    <s>/<e>/<unk> like the reference's tarred dict."""

    UNK = 2

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 synthetic=False, n_samples=256, max_len=16,
                 src_dict_size=None, trg_dict_size=None):
        src = self._require(data_file, synthetic)
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        self.dict_size = dict_size
        src_n = src_dict_size or dict_size
        trg_n = trg_dict_size or dict_size
        if src == "file":
            self._load_archive(data_file, mode, src_n, trg_n)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            for _ in range(n_samples):
                ls = rng.randint(4, max_len)
                lt = rng.randint(4, max_len)
                s = rng.randint(3, src_n, ls, dtype=np.int64)
                t = rng.randint(3, trg_n, lt, dtype=np.int64)
                self.src_ids.append(s)
                self.trg_ids.append(
                    np.concatenate([[0], t]).astype(np.int64))
                self.trg_ids_next.append(
                    np.concatenate([t, [1]]).astype(np.int64))

    @staticmethod
    def _word_id(w, n):
        """Stable hash into [3, n): crc32 is process-invariant (builtin
        str hash is salted per interpreter) and 0/1/2 stay reserved for
        <s>/<e>/<unk>."""
        import zlib
        return 3 + zlib.crc32(w.encode("utf8")) % max(n - 3, 1)

    def _load_archive(self, data_file, mode, src_n, trg_n):
        split = {"train": "train/train", "test": "test/test",
                 "gen": "gen/gen"}[mode]
        with tarfile.open(data_file) as tf:
            names = [m for m in tf.getmembers()
                     if m.name.endswith(split)]
            for m in names:
                for line in tf.extractfile(m).read().splitlines():
                    parts = line.decode("utf8").split("\t")
                    if len(parts) != 2:
                        continue
                    s = [self._word_id(w, src_n) for w in parts[0].split()]
                    t = [self._word_id(w, trg_n) for w in parts[1].split()]
                    self.src_ids.append(np.asarray(s, np.int64))
                    self.trg_ids.append(np.asarray([0] + t, np.int64))
                    self.trg_ids_next.append(np.asarray(t + [1], np.int64))

    def __getitem__(self, idx):
        return (self.src_ids[idx], self.trg_ids[idx],
                self.trg_ids_next[idx])

    def __len__(self):
        return len(self.src_ids)


class WMT16(WMT14):
    """WMT16 en-de (ref ``datasets/wmt16.py``): same item schema as
    WMT14 with configurable vocab sizes."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", synthetic=False,
                 n_samples=256, max_len=16):
        super().__init__(data_file=data_file, mode=mode,
                         dict_size=max(src_dict_size, trg_dict_size),
                         synthetic=synthetic, n_samples=n_samples,
                         max_len=max_len, src_dict_size=src_dict_size,
                         trg_dict_size=trg_dict_size)
        self.lang = lang
