"""``paddle.audio.datasets`` (ref:
``python/paddle/audio/datasets/{dataset,tess,esc50}.py``): audio
classification datasets over the framework Dataset protocol, with
on-the-fly feature extraction through :mod:`paddle_tpu.audio.features`.

``data_home()`` reads the ``PADDLE_TPU_DATA_HOME`` env var lazily (at
call time, never at import) so tests and
offline machines can point at pre-extracted archives (zero-egress: the
download only triggers when the directory is absent).
"""
from .dataset import AudioClassificationDataset  # noqa: F401
from .esc50 import ESC50  # noqa: F401
from .tess import TESS  # noqa: F401

__all__ = ["AudioClassificationDataset", "TESS", "ESC50"]
