"""ESC-50 environmental sound dataset (ref:
``python/paddle/audio/datasets/esc50.py:26``)."""
from __future__ import annotations

import collections
import csv
import os

from .dataset import AudioClassificationDataset, data_home

__all__ = ["ESC50"]


class ESC50(AudioClassificationDataset):
    """2000 5-second clips in 50 classes, 5 predefined folds; the meta
    csv carries (filename, fold, target, ...)."""

    archive = {
        "url": "https://paddleaudio.bj.bcebos.com/datasets/ESC-50-master.zip",
        "md5": "7771e4b9d86d0945acce719c7a59305a",
    }
    meta = os.path.join("ESC-50-master", "meta", "esc50.csv")
    meta_info = collections.namedtuple(
        "META_INFO",
        ("filename", "fold", "target", "category", "esc10", "src_file",
         "take"))
    audio_path = os.path.join("ESC-50-master", "audio")

    def __init__(self, mode="train", split=1, feat_type="raw",
                 archive=None, **kwargs):
        if split not in range(1, 6):
            raise AssertionError(
                f"The selected split should be 1 <= split <= 5, but got "
                f"{split}")
        if archive is not None:
            self.archive = archive
        files, labels = self._get_data(mode, split)
        super().__init__(files=files, labels=labels, feat_type=feat_type,
                         **kwargs)

    def _get_meta_info(self):
        with open(os.path.join(data_home(), self.meta)) as f:
            rows = list(csv.reader(f))
        return [self.meta_info(*r[:7]) for r in rows[1:]]

    def _get_data(self, mode, split):
        if not os.path.isdir(os.path.join(data_home(), self.audio_path)) \
                or not os.path.isfile(os.path.join(data_home(), self.meta)):
            from ...utils.download import get_path_from_url
            get_path_from_url(self.archive["url"], data_home(),
                              self.archive["md5"], decompress=True)
        files, labels = [], []
        for sample in self._get_meta_info():
            dev = int(sample.fold) == split
            if (mode == "train") != dev:
                files.append(os.path.join(data_home(), self.audio_path,
                                          sample.filename))
                labels.append(int(sample.target))
        return files, labels
