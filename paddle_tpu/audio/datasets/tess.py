"""TESS emotional speech dataset (ref:
``python/paddle/audio/datasets/tess.py:26``)."""
from __future__ import annotations

import collections
import os

from .dataset import AudioClassificationDataset, data_home

__all__ = ["TESS"]


class TESS(AudioClassificationDataset):
    """Toronto Emotional Speech Set: 2800 clips, 7 emotions, filenames
    ``<speaker>_<word>_<emotion>.wav`` under one directory. Fold split:
    every ``n_folds``-th sample (round-robin) is the dev fold."""

    archive = {
        "url": ("https://bj.bcebos.com/paddleaudio/datasets/"
                "TESS_Toronto_emotional_speech_set.zip"),
        "md5": "1465311b24d1de704c4c63e4ccc470c7",
    }
    label_list = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                  "sad"]
    meta_info = collections.namedtuple("META_INFO",
                                       ("speaker", "word", "emotion"))
    audio_path = "TESS_Toronto_emotional_speech_set"

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 archive=None, **kwargs):
        if not (isinstance(n_folds, int) and n_folds >= 1):
            raise AssertionError(
                f"the n_folds should be integer and n_folds >= 1, but "
                f"got {n_folds}")
        if split not in range(1, n_folds + 1):
            raise AssertionError(
                f"The selected split should be integer and should be "
                f"1 <= split <= {n_folds}, but got {split}")
        if archive is not None:
            self.archive = archive
        files, labels = self._get_data(mode, n_folds, split)
        super().__init__(files=files, labels=labels, feat_type=feat_type,
                         **kwargs)

    def _get_meta_info(self, files):
        return [self.meta_info(*os.path.basename(f)[:-4].split("_"))
                for f in files]

    def _get_data(self, mode, n_folds, split):
        root = os.path.join(data_home(), self.audio_path)
        if not os.path.isdir(root):
            from ...utils.download import get_path_from_url
            get_path_from_url(self.archive["url"], data_home(),
                              self.archive["md5"], decompress=True)
        wav_files = sorted(
            os.path.join(base, f)
            for base, _, fs in os.walk(root)
            for f in fs if f.lower().endswith(".wav"))
        files, labels = [], []
        for i, f in enumerate(wav_files):
            fold = i % n_folds + 1
            if (mode == "train") == (fold != split):
                emotion = os.path.basename(f)[:-4].split("_")[-1].lower()
                if emotion not in self.label_list:
                    continue
                files.append(f)
                labels.append(self.label_list.index(emotion))
        return files, labels
