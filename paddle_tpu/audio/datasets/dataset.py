"""Base audio-classification dataset (ref:
``python/paddle/audio/datasets/dataset.py``)."""
from __future__ import annotations

import os

import numpy as np

from ...io import Dataset
from .. import features as _features

def data_home() -> str:
    """Dataset cache root — resolved lazily so ``PADDLE_TPU_DATA_HOME``
    set after import (tests, launchers) is honored."""
    return os.environ.get(
        "PADDLE_TPU_DATA_HOME",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "datasets"))

feat_funcs = {
    "raw": None,
    "melspectrogram": _features.MelSpectrogram,
    "mfcc": _features.MFCC,
    "logmelspectrogram": _features.LogMelSpectrogram,
    "spectrogram": _features.Spectrogram,
}


class AudioClassificationDataset(Dataset):
    """(waveform-or-feature, label) pairs from audio files (ref
    ``dataset.py AudioClassificationDataset``)."""

    def __init__(self, files, labels, feat_type="raw", sample_rate=None,
                 **kwargs):
        super().__init__()
        if feat_type not in feat_funcs:
            raise RuntimeError(
                f"Unknown feat_type: {feat_type}, it must be one in "
                f"{list(feat_funcs)}")
        self.files = list(files)
        self.labels = list(labels)
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self._feat_layer = None
        self._feat_kwargs = kwargs

    def _convert_to_record(self, idx):
        from .. import backends
        wav, sr = backends.load(self.files[idx])
        wav = np.asarray(wav, np.float32)
        if wav.ndim > 1:
            wav = wav.mean(axis=0)  # mono
        if self.feat_type == "raw":
            return wav, self.labels[idx]
        if self._feat_layer is None:
            kw = dict(self._feat_kwargs)
            kw.setdefault("sr", self.sample_rate or sr)
            self._feat_layer = feat_funcs[self.feat_type](**kw)
        from ...tensor import Tensor
        feat = self._feat_layer(Tensor(wav[None, :]))
        return np.asarray(feat._data)[0], self.labels[idx]

    def __getitem__(self, idx):
        return self._convert_to_record(idx)

    def __len__(self):
        return len(self.files)
