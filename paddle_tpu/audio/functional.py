"""``paddle.audio.functional`` (ref: ``python/paddle/audio/functional/
functional.py``): mel scales, filterbanks, dB conversion, DCT."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def hz_to_mel(freq, htk=False):
    """Ref ``functional.py:22`` — slaney (default) or HTK mel scale."""
    scalar = isinstance(freq, (int, float))
    f = jnp.asarray(freq, jnp.float32) if scalar else _arr(freq)
    if htk:
        mel = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(
                            jnp.maximum(f, 1e-10) / min_log_hz) / logstep,
                        mel)
    return float(mel) if scalar else Tensor(mel)


def mel_to_hz(mel, htk=False):
    scalar = isinstance(mel, (int, float))
    m = jnp.asarray(mel, jnp.float32) if scalar else _arr(mel)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = jnp.where(m >= min_log_mel,
                       min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                       hz)
    return float(hz) if scalar else Tensor(hz)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    lo = hz_to_mel(float(f_min), htk)
    hi = hz_to_mel(float(f_max), htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return mel_to_hz(Tensor(mels), htk)


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]
    (ref ``functional.py:186``)."""
    f_max = f_max or sr / 2
    fftfreqs = fft_frequencies(sr, n_fft)._data
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)._data
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(weights)


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10 with clamping (ref ``functional.py:259``)."""
    s = _arr(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec) if isinstance(spect, Tensor) else log_spec


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc] (ref ``functional.py:303``)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        scale = jnp.full((1, n_mfcc), math.sqrt(2.0 / n_mels))
        scale = scale.at[0, 0].set(math.sqrt(1.0 / n_mels))
        dct = dct * scale
    else:
        dct = dct * 2.0
    return Tensor(dct)
