"""``paddle.audio.features`` (ref: ``python/paddle/audio/features/
layers.py``): Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC as
nn Layers — each forward is one fused XLA program (stft + matmul + log)."""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..tensor import Tensor
from .. import signal as _signal
from .functional import compute_fbank_matrix, power_to_db, create_dct
from .window import get_window

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = get_window(window, self.win_length, fftbins=True,
                                     dtype=dtype)

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                            window=self.fft_window, center=self.center,
                            pad_mode=self.pad_mode)
        return spec.abs() ** self.power


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.n_mels = n_mels
        self.fbank_matrix = compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype)

    def forward(self, x):
        spec = self._spectrogram(x)  # [..., freq, frames]
        return self.fbank_matrix @ spec


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return power_to_db(mel, ref_value=self.ref_value, amin=self.amin,
                           top_db=self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct_matrix = create_dct(n_mfcc=n_mfcc, n_mels=n_mels,
                                     dtype=dtype)

    def forward(self, x):
        log_mel = self._log_melspectrogram(x)  # [..., n_mels, frames]
        from ..ops.linalg import matmul
        from ..ops.manipulation import transpose
        # dct^T @ log_mel -> [..., n_mfcc, frames]
        ndim = len(log_mel.shape)
        perm = list(range(ndim - 2)) + [ndim - 1, ndim - 2]
        return transpose(matmul(transpose(log_mel, perm), self.dct_matrix),
                         perm)
