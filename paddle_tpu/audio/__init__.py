"""``paddle.audio`` (ref: ``python/paddle/audio/``): feature layers +
functional DSP + wav IO backends (stdlib ``wave``-based PCM16, like the
reference's default wave_backend)."""
from . import functional as _func_mod
from . import features  # noqa: F401
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from .backends import info, load, save  # noqa: F401
from .window import get_window  # noqa: F401


class functional:  # namespace mirroring paddle.audio.functional
    from .functional import (  # noqa: F401
        hz_to_mel, mel_to_hz, mel_frequencies, fft_frequencies,
        compute_fbank_matrix, power_to_db, create_dct,
    )
    from .window import get_window  # noqa: F401


__all__ = ["functional", "features", "get_window", "backends", "datasets", "info",
           "load", "save"]
