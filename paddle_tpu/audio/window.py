"""Window functions (ref: ``python/paddle/audio/functional/window.py``
get_window + registered families)."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["get_window"]


def _extend(M, sym):
    return (M + 1, True) if not sym else (M, False)


def _truncate(w, trunc):
    return w[:-1] if trunc else w


def _general_cosine(M, a, sym):
    M, trunc = _extend(M, sym)
    fac = jnp.linspace(-math.pi, math.pi, M)
    w = jnp.zeros(M)
    for k, ak in enumerate(a):
        w = w + ak * jnp.cos(k * fac)
    return _truncate(w, trunc)


def _hamming(M, sym=True):
    return _general_cosine(M, [0.54, 0.46], sym)


def _hann(M, sym=True):
    return _general_cosine(M, [0.5, 0.5], sym)


def _blackman(M, sym=True):
    return _general_cosine(M, [0.42, 0.50, 0.08], sym)


def _nuttall(M, sym=True):
    return _general_cosine(M, [0.3635819, 0.4891775, 0.1365995, 0.0106411],
                           sym)


def _gaussian(M, std, sym=True):
    M, trunc = _extend(M, sym)
    n = jnp.arange(M) - (M - 1) / 2
    return _truncate(jnp.exp(-0.5 * (n / std) ** 2), trunc)


def _exponential(M, center=None, tau=1.0, sym=True):
    M, trunc = _extend(M, sym)
    if center is None:
        center = (M - 1) / 2
    n = jnp.arange(M)
    return _truncate(jnp.exp(-jnp.abs(n - center) / tau), trunc)


def _triang(M, sym=True):
    M, trunc = _extend(M, sym)
    n = jnp.arange(1, (M + 1) // 2 + 1)
    if M % 2 == 0:
        w = (2 * n - 1.0) / M
        w = jnp.concatenate([w, w[::-1]])
    else:
        w = 2 * n / (M + 1.0)
        w = jnp.concatenate([w, w[-2::-1]])
    return _truncate(w, trunc)


def _bohman(M, sym=True):
    M, trunc = _extend(M, sym)
    fac = jnp.abs(jnp.linspace(-1, 1, M))
    w = (1 - fac) * jnp.cos(math.pi * fac) + \
        1.0 / math.pi * jnp.sin(math.pi * fac)
    w = w.at[0].set(0).at[-1].set(0)
    return _truncate(w, trunc)


def _cosine(M, sym=True):
    M, trunc = _extend(M, sym)
    return _truncate(jnp.sin(math.pi / M * (jnp.arange(M) + 0.5)), trunc)


def _tukey(M, alpha=0.5, sym=True):
    M, trunc = _extend(M, sym)
    if alpha <= 0:
        w = jnp.ones(M)
    elif alpha >= 1:
        w = _hann(M, sym=True)
        return _truncate(w, trunc)
    else:
        n = jnp.arange(M)
        width = int(alpha * (M - 1) / 2)
        w = jnp.ones(M)
        edge = 0.5 * (1 + jnp.cos(math.pi * (-1 + 2.0 * n / alpha / (M - 1))))
        tail = 0.5 * (1 + jnp.cos(
            math.pi * (-2.0 / alpha + 1 + 2.0 * n / alpha / (M - 1))))
        w = jnp.where(n <= width, edge, w)
        w = jnp.where(n >= M - width - 1, tail, w)
    return _truncate(w, trunc)


_WINDOWS = {
    "hamming": _hamming,
    "hann": _hann,
    "blackman": _blackman,
    "nuttall": _nuttall,
    "gaussian": _gaussian,
    "exponential": _exponential,
    "triang": _triang,
    "bohman": _bohman,
    "cosine": _cosine,
    "tukey": _tukey,
}


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """``paddle.audio.functional.get_window``: name or (name, arg) tuple;
    ``fftbins=True`` means periodic (sym=False)."""
    sym = not fftbins
    if isinstance(window, str):
        name, args = window, ()
    elif isinstance(window, tuple):
        name, args = window[0], window[1:]
    else:
        raise ValueError(f"unsupported window spec: {window!r}")
    fn = _WINDOWS.get(name)
    if fn is None:
        raise ValueError(f"unknown window '{name}' "
                         f"(available: {sorted(_WINDOWS)})")
    w = fn(win_length, *args, sym=sym)
    from ..framework.dtype import to_jax_dtype
    return Tensor(w.astype(to_jax_dtype(dtype)))
