"""``paddle.audio.backends`` (ref:
``python/paddle/audio/backends/wave_backend.py``): wav info/load/save
over the stdlib ``wave`` module — no native soundfile dependency, same
PCM16 semantics as the reference's default backend."""
from __future__ import annotations

import wave
from collections import namedtuple

import numpy as np

from ..tensor import Tensor

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]

AudioInfo = namedtuple(
    "AudioInfo", ["sample_rate", "num_frames", "num_channels",
                  "bits_per_sample", "encoding"])


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name):
    if backend_name != "wave_backend":
        raise NotImplementedError(
            f"only the stdlib 'wave_backend' ships in-tree, got "
            f"{backend_name!r} (the reference's soundfile backend is an "
            f"optional external dependency there too)")


def info(filepath) -> AudioInfo:
    """Signal information of a wav file (or file object). Caller-provided
    file objects are left open (only handles opened here are closed)."""
    own = not hasattr(filepath, "read")
    file_obj = open(filepath, "rb") if own else filepath
    try:
        try:
            f = wave.open(file_obj)
        except (wave.Error, EOFError):
            raise NotImplementedError(
                "only PCM wav is supported by the in-tree wave backend")
        width = f.getsampwidth()
        # 8-bit wav is unsigned by spec; wider PCM is signed
        return AudioInfo(f.getframerate(), f.getnframes(),
                         f.getnchannels(), width * 8,
                         "PCM_U" if width == 1 else "PCM_S")
    finally:
        if own:
            file_obj.close()


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Returns (Tensor waveform, sample_rate). float32 in [-1, 1] when
    ``normalize`` else raw int16; (C, T) when ``channels_first``."""
    own = not hasattr(filepath, "read")
    file_obj = open(filepath, "rb") if own else filepath
    try:
        try:
            f = wave.open(file_obj)
        except (wave.Error, EOFError):
            raise NotImplementedError(
                "only PCM wav is supported by the in-tree wave backend")
        sr = f.getframerate()
        channels = f.getnchannels()
        if f.getsampwidth() != 2:
            raise NotImplementedError("only 16-bit PCM wav is supported")
        f.setpos(min(frame_offset, f.getnframes()))
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(max(n, 0))
    finally:
        if own:
            file_obj.close()
    data = np.frombuffer(raw, dtype="<i2").reshape(-1, channels)
    if normalize:
        data = (data.astype(np.float32) / 32768.0)
    arr = data.T if channels_first else data
    return Tensor(np.ascontiguousarray(arr)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_S", bits_per_sample=16):
    """Write a (C, T) (or (T, C)) waveform Tensor/array as 16-bit PCM."""
    if bits_per_sample != 16 or encoding != "PCM_S":
        raise NotImplementedError(
            "the in-tree wave backend writes 16-bit PCM_S only")
    a = np.asarray(src._data if isinstance(src, Tensor) else src)
    if a.ndim == 1:
        a = a[:, None]                   # mono -> (T, 1) either layout
    elif channels_first:
        a = a.T                          # (C, T) -> (T, C)
    if a.dtype.kind == "f":
        a = np.clip(a, -1.0, 1.0)
        a = (a * 32767.0).astype("<i2")
    else:
        a = a.astype("<i2")
    target = filepath if hasattr(filepath, "write") else str(filepath)
    with wave.open(target, "wb") as f:
        f.setnchannels(a.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(a).tobytes())
