"""``paddle.cost_model`` (ref: ``python/paddle/cost_model/cost_model.py:25``).

The reference pairs a profiler-measured path with a shipped table of
GPU op times (``static_op_benchmark.json``, measured on their CI fleet).
Here the analytic leg is stronger than a lookup table: XLA's own cost
analysis gives exact FLOPs / bytes-accessed for any compiled program
(``analytic_cost``), which the auto-tuner and bench already rely on. The
measured leg (``profile_measure``) runs a static Program under the
profiler and returns per-event wall times; ``static_cost_data`` reads a
bundled/locally-generated table with the reference's schema
(``benchmark_ops`` regenerates it on the current host/device).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

__all__ = ["CostModel"]

_TABLE = os.path.join(os.path.dirname(__file__), "static_op_benchmark.json")


class CostModel:
    def __init__(self):
        self._static_cost_data = None

    # -- toy program, mirrors the reference docstring example -------------
    def build_program(self):
        import paddle_tpu as paddle
        from paddle_tpu import static

        paddle.enable_static()
        main_program = static.Program()
        startup_program = static.Program()
        with static.program_guard(main_program=main_program,
                                  startup_program=startup_program):
            data = static.data(name="X", shape=[10, 1], dtype="float32")
            hidden = static.nn.fc(data, 10)
            loss = paddle.mean(hidden)
            paddle.optimizer.SGD(learning_rate=0.01).minimize(loss)
        paddle.disable_static()
        return startup_program, main_program

    # -- measured: run under the profiler, return per-event times ---------
    def profile_measure(self, startup_program, main_program, device="tpu",
                        fetch_cost_list=("time",)):
        import paddle_tpu as paddle
        from paddle_tpu import profiler, static

        paddle.enable_static()
        try:
            exe = static.Executor()
            exe.run(startup_program)
            x = np.random.random(size=(10, 1)).astype("float32")
            prof = profiler.Profiler()
            prof.start()
            exe.run(main_program, feed={"X": x}, fetch_list=[])
            prof.stop()
        finally:
            paddle.disable_static()
        from ..profiler import SummaryView
        from .. import core as _core
        view = SummaryView(_core.tracer_events())
        return {s.name: {"time_ms": s.total_ns / 1e6, "calls": s.calls}
                for s in view.rows}

    # -- analytic: XLA cost analysis of an arbitrary jitted fn ------------
    @staticmethod
    def analytic_cost(fn, *example_args):
        """{'flops', 'bytes accessed', ...} for the compiled program."""
        import jax
        lowered = jax.jit(fn).lower(*example_args)
        cost = lowered.compile().cost_analysis()
        # older jax wraps the analysis dict in a per-program list
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return cost

    # -- static table, reference schema -----------------------------------
    def static_cost_data(self):
        if not os.path.exists(_TABLE):
            raise FileNotFoundError(
                f"{_TABLE} not found; run CostModel.benchmark_ops() once on "
                f"this host to generate it")
        with open(_TABLE) as f:
            self._static_cost_data = json.load(f)
        return self._static_cost_data

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        if op_name is None:
            raise ValueError("op_name should not be empty")
        if self._static_cost_data is None:
            self.static_cost_data()
        op_cost = {}
        for op_data in self._static_cost_data:
            if op_data["op"] == op_name and dtype in op_data["config"]:
                key = ("paddle_gpu_time" if forward
                       else "paddle_gpu_time_backward")
                op_cost["op_time"] = op_data[key]
                op_cost["config"] = op_data["config"]
        if not op_cost:
            raise KeyError(
                f"no cost-table row for op {op_name!r} with dtype "
                f"{dtype!r}; the table may have been generated on a "
                f"different device kind — re-run "
                f"CostModel.benchmark_ops() on this host")
        return op_cost

    # -- table generation (replaces the reference's CI benchmark job) -----
    @staticmethod
    def benchmark_ops(path=_TABLE, iters=20):
        """Measure a standard op set fwd+bwd on the current device and write
        the table. Times are ms; device kind recorded per row."""
        import jax
        import jax.numpy as jnp

        kind = jax.devices()[0].device_kind
        key = jax.random.key(0)
        x2d = jax.random.normal(key, (256, 256))
        ximg = jax.random.normal(key, (8, 16, 32, 32))
        w3 = jax.random.normal(key, (16, 16, 3, 3))
        specs = {
            "matmul": (x2d, lambda x: jnp.matmul(x, x).sum()),
            "relu": (x2d, lambda x: jax.nn.relu(x).sum()),
            "softmax": (x2d, lambda x: jax.nn.softmax(x).sum()),
            "conv2d": (ximg, lambda x: jax.lax.conv_general_dilated(
                x, w3, (1, 1), "SAME").sum()),
            "layer_norm": (x2d, lambda x: (
                (x - x.mean(-1, keepdims=True))
                / jnp.sqrt(x.var(-1, keepdims=True) + 1e-5)).sum()),
        }
        rows = []
        for name, (inp, f) in specs.items():
            # each iteration compiles a DIFFERENT op on purpose — this
            # is the benchmark that builds the cost table, not a hot path
            fwd = jax.jit(f)  # tpu-lint: disable=TPU001
            bwd = jax.jit(jax.grad(f))  # tpu-lint: disable=TPU001

            def timed(g):
                jax.block_until_ready(g(inp))  # compile + warm, fully
                t0 = time.perf_counter()
                for _ in range(iters):
                    jax.block_until_ready(g(inp))
                return (time.perf_counter() - t0) / iters * 1e3

            rows.append({"op": name, "config": f"float32 device={kind}",
                         "paddle_gpu_time": timed(fwd),
                         "paddle_gpu_time_backward": timed(bwd)})
        with open(path, "w") as f:
            json.dump(rows, f, indent=1)
        return rows
