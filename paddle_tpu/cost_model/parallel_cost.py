"""Analytic parallel-config cost model (ref:
``python/paddle/distributed/auto_parallel/static/cost/`` — comp/comm op
cost classes + estimator feeding the tuner).

Predicts per-step time and per-chip memory for a transformer training
config on a :class:`~paddle_tpu.distributed.auto_parallel.cluster.Cluster`.
The point is ORDERING and OOM pruning, not microsecond accuracy: the
auto-tuner uses it to rank candidates best-first and to skip configs
that cannot fit, so measured trials start near the optimum (VERDICT r04
item 6: cost_model wired into auto_tuner).

Model description (dict): ``n_params`` (total), ``num_layers``,
``hidden_size``, ``seq_len``, optional ``vocab_size``.
"""
from __future__ import annotations

__all__ = ["predict_step_time", "predict_memory_bytes", "predict"]

# fraction of peak the MXU sustains on well-tiled transformer matmuls
# (bench r03 measured 0.29-0.36 across ResNet/BERT/GPT on v5e)
_MFU_EFF = 0.35
# bytes of saved activation per token per layer (bf16, post-fusion);
# with full recompute only the layer inputs survive
_ACT_BYTES_FULL = 34.0
_ACT_BYTES_REMAT = 4.0


def _deg(cfg, key):
    v = cfg.get(key)
    return int(v) if v else 1


def predict_memory_bytes(model, cfg, cluster, global_batch_size=None):
    """Per-chip HBM: params + grads + AdamW state (+master) + acts.

    Activations count the 1F1B in-flight depth: a pipeline stage keeps
    up to ``min(pp, micro_steps)`` micro-batches of its layers' saved
    activations resident, not one. With ``vocab_size`` present the lm
    head's logits buffer (the dominant single activation for large
    vocabularies) is counted too."""
    n = float(model["n_params"])
    L = int(model.get("num_layers", 1))
    H = int(model.get("hidden_size", 1))
    S = int(model.get("seq_len", 1))
    V = int(model.get("vocab_size", 0))
    dp, mp = _deg(cfg, "dp_degree"), _deg(cfg, "mp_degree")
    pp, shard = _deg(cfg, "pp_degree"), _deg(cfg, "sharding_degree")
    mbs = int(cfg.get("micro_batch_size") or 1)
    remat = bool(cfg.get("use_recompute", False))
    gbs = global_batch_size or cfg.get("global_batch_size")
    micro_steps = max(int(gbs) // max(dp * shard * mbs, 1), 1) if gbs \
        else pp
    in_flight = min(pp, micro_steps)

    n_local = n / (mp * pp)                  # bf16 params + bf16 grads
    weights = n_local * 2 + n_local * 2
    # AdamW m, v + fp32 master: ZeRO partitions these over sharding
    opt = n_local * 12 / max(shard, 1)
    act_per_tok = _ACT_BYTES_REMAT if remat else _ACT_BYTES_FULL
    acts = mbs * S * H * (L / pp) / mp * act_per_tok * in_flight
    if V:
        # bf16 logits + fp32 softmax/CE working set on the last stage
        acts += mbs * S * V * 6.0 / mp
    return weights + opt + acts


def predict_step_time(model, cfg, cluster, global_batch_size=None):
    """Seconds per optimizer step on ``cluster`` for this config."""
    n = float(model["n_params"])
    L = int(model.get("num_layers", 1))
    H = int(model.get("hidden_size", 1))
    S = int(model.get("seq_len", 1))
    dp, mp = _deg(cfg, "dp_degree"), _deg(cfg, "mp_degree")
    pp, shard = _deg(cfg, "pp_degree"), _deg(cfg, "sharding_degree")
    mbs = int(cfg.get("micro_batch_size") or 1)
    remat = bool(cfg.get("use_recompute", False))
    gbs = int(global_batch_size or cfg.get("global_batch_size")
              or dp * shard * mbs)
    data_par = dp * shard                      # both shard the batch
    micro_steps = max(gbs // max(data_par * mbs, 1), 1)

    # -- compute: 6N per token fwd+bwd + causal attention flops; remat
    # re-runs the forward (~+33% of fwd+bwd's 3 passes)
    flops_tok = 6.0 * n + 6.0 * L * S * H
    if remat:
        flops_tok *= 4.0 / 3.0
    tokens_step = gbs * S
    compute = (flops_tok * tokens_step
               / (cluster.peak_flops * _MFU_EFF)
               / max(data_par * mp * pp, 1))

    # -- pipeline bubble: (pp-1) idle micro-slots per 1F1B round
    compute *= 1.0 + (pp - 1) / float(micro_steps)

    # -- mp collectives: 4 allgather/reduce-scatter-class transfers per
    # layer per micro-batch of the (mbs, S, H) bf16 activation
    comm = 0.0
    if mp > 1:
        act_bytes = 2.0 * mbs * S * H
        comm += (4.0 * (L / pp) * act_bytes * (mp - 1) / mp
                 * micro_steps / cluster.bandwidth(mp))
    # -- dp/sharding gradient reduction: ring allreduce 2x grad bytes
    if data_par > 1:
        grad_bytes = 2.0 * n / (mp * pp)
        comm += (2.0 * grad_bytes * (data_par - 1) / data_par
                 / cluster.bandwidth(data_par))
    # -- pp activation sends: one (mbs, S, H) per boundary per micro
    if pp > 1:
        comm += (2.0 * mbs * S * H * (pp - 1) * micro_steps
                 / cluster.bandwidth(pp))
    return compute + comm


def predict(model, cfg, cluster, global_batch_size=None):
    """(seconds_per_step, memory_bytes_per_chip, fits) triple."""
    t = predict_step_time(model, cfg, cluster, global_batch_size)
    m = predict_memory_bytes(model, cfg, cluster, global_batch_size)
    return t, m, m <= cluster.hbm_bytes * 0.92  # runtime reserve
