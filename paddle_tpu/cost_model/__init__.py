from .cost_model import CostModel  # noqa: F401
from .parallel_cost import (  # noqa: F401
    predict, predict_memory_bytes, predict_step_time,
)

__all__ = ["CostModel", "predict", "predict_memory_bytes",
           "predict_step_time"]
