"""paddle_tpu: a TPU-native deep-learning framework.

Brand-new framework with the capabilities of the PaddlePaddle reference
(surveyed in /root/repo/SURVEY.md), designed TPU-first:

 - compute path: jax/XLA (single compiled program per step, MXU-shaped
   matmuls, bf16-first AMP) with Pallas kernels for the hot fused ops
 - autograd: eager tape over ``jax.vjp`` for dygraph ergonomics; functional
   ``jax.grad`` under ``to_static``/jit for the fast path
 - distributed: ``jax.sharding.Mesh`` + GSPMD + shard_map collectives over
   ICI/DCN replace ProcessGroup/NCCL/TCPStore wholesale
 - runtime around the compute path (tracing, flags, IO) backed by a native
   C++ core where the reference is native

Public API mirrors ``paddle.*`` so reference users can switch directly.
"""
from __future__ import annotations

import builtins as _builtins

__version__ = "0.1.0"

# -- core framework ---------------------------------------------------------
from .framework import (  # noqa: F401
    dtype, iinfo, finfo, get_default_dtype, set_default_dtype,
    set_flags, get_flags,
    seed, get_rng_state, set_rng_state,
    CPUPlace, TPUPlace, CUDAPlace, CustomPlace, XPUPlace, CUDAPinnedPlace,
    set_device, get_device, device_count,
    is_compiled_with_cuda, is_compiled_with_rocm, is_compiled_with_xpu,
    is_compiled_with_tpu, is_compiled_with_cinn,
    is_compiled_with_custom_device,
)
from .framework.random import get_cuda_rng_state, set_cuda_rng_state  # noqa: F401
from .framework.lazy_init import LazyGuard  # noqa: F401
from .framework import (  # dtype singletons  # noqa: F401
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
)
bool = bool_  # paddle.bool (shadows builtin inside this namespace only)

# -- tensor + autograd ------------------------------------------------------
from .tensor import Tensor, to_tensor, is_tensor, set_printoptions  # noqa: F401
from .autograd import (no_grad, enable_grad, set_grad_enabled, grad,  # noqa: F401
                       is_grad_enabled)
from . import autograd  # noqa: F401


def disable_signal_handler():
    """Parity shim (ref ``framework.py disable_signal_handler``): the
    reference unhooks its C++ fault handlers; this build installs none, so
    there is nothing to disable."""

# -- ops (flat namespace) ---------------------------------------------------
from .ops import *  # noqa: F401,F403
from .ops.linalg import einsum  # noqa: F401

# -- submodules (grown incrementally; see SURVEY.md §7 build order) ---------
from . import amp  # noqa: F401
from . import linalg  # noqa: F401


def _optional_submodules():
    """Import API-surface submodules that exist; grown as the build widens."""
    import importlib
    names = ["nn", "optimizer", "io", "jit", "device", "distributed",
             "vision", "metric", "hapi", "profiler", "static", "incubate",
             "sparse", "distribution", "text", "audio", "quantization",
             "utils", "fft", "signal", "models", "callbacks", "regularizer",
             "inference", "geometric", "hub", "cost_model", "reader",
             "version", "sysconfig",
             "onnx"]
    loaded = {}
    for n in names:
        try:
            loaded[n] = importlib.import_module(f".{n}", __name__)
        except ModuleNotFoundError as e:
            if f"paddle_tpu.{n}" not in str(e):
                raise
    return loaded


globals().update(_optional_submodules())

# convenience top-level re-exports that depend on optional modules
from .batch import batch  # noqa: F401
try:
    from .framework.io_state import save, load  # noqa: F401
except ImportError:
    pass
try:
    from .hapi.model import Model  # noqa: F401
    from .hapi.summary import summary, flops  # noqa: F401
except ImportError:
    pass
try:
    from .nn.layer.layers import ParamAttr  # noqa: F401
except ImportError:
    pass
try:
    from .jit.api import enable_static, disable_static, in_dynamic_mode  # noqa: F401
except ImportError:
    pass
try:
    from .distributed.parallel import DataParallel  # noqa: F401
except ImportError:
    pass
