"""``paddle.autograd`` functional API: lazy Jacobian / Hessian (ref:
``python/paddle/autograd/autograd.py:30 Jacobian``, ``:450 jacobian``,
``:542 hessian``).

The reference evaluates rows lazily through repeated dygraph backward
calls; here each row is one tape :func:`~paddle_tpu.autograd.grad` with
a one-hot cotangent (rows cache at row granularity, same contract).
``ys`` must be tape-recorded outputs of ``xs``.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Jacobian", "Hessian", "jacobian", "hessian"]


def _is_seq(x):
    return isinstance(x, (list, tuple))


class Jacobian:
    """Lazy d(ys)/d(xs) for one (ys, xs) Tensor pair.

    ``batch_axis=None``: xs [N], ys [M] -> shape [M, N];
    ``batch_axis=0``:    xs [B, N], ys [B, M] -> shape [B, M, N].
    Indexing evaluates (and caches) only the rows the index touches.
    """

    def __init__(self, ys, xs, batch_axis=None, create_graph=False):
        if batch_axis not in (None, 0):
            raise ValueError("batch_axis must be None or 0, got "
                             f"{batch_axis!r}")
        nd_ok = (1, 2) if batch_axis == 0 else (0, 1)
        if ys.ndim not in nd_ok or xs.ndim not in nd_ok:
            raise ValueError(
                f"with batch_axis={batch_axis}, ys/xs must be "
                f"{nd_ok}-dimensional; got ys.ndim={ys.ndim}, "
                f"xs.ndim={xs.ndim}")
        self._ys, self._xs = ys, xs
        self._batch = batch_axis == 0
        self._create_graph = create_graph
        self._rows: dict = {}

    @property
    def shape(self):
        ys, xs = self._ys, self._xs
        if self._batch:
            # np.prod(()) == 1 covers scalars; a genuine 0-size dim
            # must stay 0, not be coerced to 1
            return [ys.shape[0], int(np.prod(ys.shape[1:])),
                    int(np.prod(xs.shape[1:]))]
        return [int(np.prod(ys.shape)), int(np.prod(xs.shape))]

    def _n_rows(self):
        return self.shape[1] if self._batch else self.shape[0]

    def _row(self, m):
        if m not in self._rows:
            import jax.numpy as jnp
            from .autograd import grad
            from .tensor import Tensor
            ys = self._ys
            dt = ys._data.dtype  # cotangent must match the output aval
            if not jnp.issubdtype(dt, jnp.floating):
                dt = jnp.float32
            if self._batch:
                cot = jnp.zeros(ys.shape, dt)
                cot = cot.reshape(ys.shape[0], -1).at[:, m].set(1.0) \
                    .reshape(ys.shape)
            else:
                cot = jnp.zeros(ys.shape, dt) if ys.ndim else \
                    jnp.ones((), dt)
                if ys.ndim:
                    cot = cot.ravel().at[m].set(1.0).reshape(ys.shape)
            (g,) = grad(ys, [self._xs], grad_outputs=Tensor(cot),
                        retain_graph=True,
                        create_graph=self._create_graph,
                        allow_unused=True)
            if g is None:
                from .ops.creation import zeros_like
                g = zeros_like(self._xs)
            self._rows[m] = g
        return self._rows[m]

    def _materialize(self, rows):
        """Stack the requested rows into one Tensor along the row axis."""
        from . import ops
        parts = [self._row(m) for m in rows]
        if self._batch:
            # each part is [B, N_flat...] -> [B, len(rows), N]
            parts = [ops.reshape(p, [p.shape[0], 1, -1]) for p in parts]
            return ops.concat(parts, axis=1)
        parts = [ops.reshape(p, [1, -1]) for p in parts]
        return ops.concat(parts, axis=0)

    def _rows_touched(self, idx):
        """Row indices (along the row axis) the index needs, or None
        for 'all' (fancy/unsupported index forms)."""
        M = self._n_rows()
        parts = idx if isinstance(idx, tuple) else (idx,)
        row_pos = 1 if self._batch else 0
        if len(parts) <= row_pos:
            return None  # row axis untouched by the index -> all rows
        r = parts[row_pos]
        if isinstance(r, int):
            if not -M <= r < M:
                raise IndexError(
                    f"row index {r} out of range for Jacobian with {M} "
                    f"rows")
            return [r % M]
        if isinstance(r, slice):
            return list(range(*r.indices(M)))
        return None

    def __getitem__(self, idx):
        # lazy contract: evaluate (and cache) ONLY the rows the index
        # touches — one backward per new row
        rows = self._rows_touched(idx)
        if rows is None:
            return self._materialize(range(self._n_rows()))[idx]
        sub = self._materialize(rows)
        # remap the row component of the index into the submatrix
        parts = list(idx) if isinstance(idx, tuple) else [idx]
        row_pos = 1 if self._batch else 0
        r = parts[row_pos]
        parts[row_pos] = 0 if isinstance(r, int) else slice(None)
        return sub[tuple(parts) if len(parts) > 1 else parts[0]]

    def __array__(self, dtype=None):
        a = np.asarray(self._materialize(range(self._n_rows()))._data)
        return a.astype(dtype) if dtype is not None else a


class Hessian(Jacobian):
    """d2(ys)/d(xs)2 for scalar ``ys`` (per batch element when
    ``batch_axis=0``): the Jacobian of the create_graph first-order
    gradient (ref ``autograd.py:183``)."""

    def __init__(self, ys, xs, batch_axis=None):
        from .autograd import grad
        n = int(np.prod(ys.shape) or 1)
        expect = ys.shape[0] if batch_axis == 0 else 1
        if n != (expect if batch_axis == 0 else 1):
            raise ValueError("hessian requires scalar ys (one value per "
                             f"batch element); got shape {list(ys.shape)}")
        (g,) = grad(ys, [xs], retain_graph=True, create_graph=True)
        super().__init__(g, xs, batch_axis=batch_axis)


def _nest(ys, xs, batch_axis, cls):
    if _is_seq(ys):
        return tuple(_nest(y, xs, batch_axis, cls) for y in ys)
    if _is_seq(xs):
        return tuple(cls(ys, x, batch_axis) for x in xs)
    return cls(ys, xs, batch_axis)


def jacobian(ys, xs, batch_axis=None):
    """ref ``autograd.py:450``: tuple nesting mirrors (ys, xs)."""
    return _nest(ys, xs, batch_axis, Jacobian)


def hessian(ys, xs, batch_axis=None):
    """ref ``autograd.py:542``: ``ys`` must be scalar(-per-batch)."""
    if _is_seq(ys):
        raise ValueError("hessian expects a single scalar ys")
    if _is_seq(xs):
        # symmetric block structure: row blocks d/dx_i of grads wrt x_j
        n = int(np.prod(ys.shape))
        expect = ys.shape[0] if batch_axis == 0 else 1
        if n != expect:
            raise ValueError("hessian requires scalar ys (one value per "
                             f"batch element); got shape {list(ys.shape)}")
        from .autograd import grad
        gs = grad(ys, list(xs), retain_graph=True, create_graph=True)
        return tuple(tuple(Jacobian(g, x, batch_axis) for x in xs)
                     for g in gs)
    return Hessian(ys, xs, batch_axis)
