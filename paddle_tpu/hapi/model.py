"""High-level Model API (ref: ``python/paddle/hapi/model.py:1741 Model.fit``).

TPU-native: `prepare()` builds ONE jitted train-step program
(forward + loss + backward + optimizer update, functional over params/opt
state) — the entire per-step work is a single XLA executable, which is the
performance contract the reference approximates with its static graph mode.
"""
from __future__ import annotations

import os
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..nn.layer.layers import Layer
from ..metric import Metric
from ..framework import random as _random
from ..observability import get_telemetry
from ..observability.trace import get_tracer
from .. import autograd
from .callbacks import config_callbacks

__all__ = ["Model", "LossScalar"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _arrays(batch):
    out = []
    for b in _to_list(batch):
        if isinstance(b, Tensor):
            out.append(b._data)
        else:
            out.append(jnp.asarray(np.asarray(b)))
    return out


def _unwrap(o):
    return o._sync() if isinstance(o, LossScalar) else o


class LossScalar:
    """Lazy handle over the on-device loss scalar.

    ``train_batch`` returns as soon as the step is DISPATCHED; the
    device→host copy (the per-step sync that stalls the TPU pipeline,
    tpu-lint TPU007) happens at the first read — ``float()``, a
    comparison, formatting — which in the fit loop is the callback/log
    cadence, not every batch. Reads memoize, so the sync is paid once.
    Behaves like the float it wraps everywhere the hapi loop and the
    stock callbacks consume it."""

    __slots__ = ("_arr", "_val")

    def __init__(self, arr):
        self._arr = arr
        self._val = None

    def _sync(self):
        v = self._val
        if v is None:
            v = self._val = float(np.asarray(self._arr))
            self._arr = None  # drop the device buffer once materialized
        return v

    def __float__(self):
        return self._sync()

    def __repr__(self):
        return repr(self._sync())

    def __str__(self):
        return str(self._sync())

    def __format__(self, spec):
        return format(self._sync(), spec)

    def __bool__(self):
        return bool(self._sync())

    def __hash__(self):
        return hash(self._sync())

    def __eq__(self, o):
        return self._sync() == _unwrap(o)

    def __lt__(self, o):
        return self._sync() < _unwrap(o)

    def __le__(self, o):
        return self._sync() <= _unwrap(o)

    def __gt__(self, o):
        return self._sync() > _unwrap(o)

    def __ge__(self, o):
        return self._sync() >= _unwrap(o)

    def __add__(self, o):
        return self._sync() + _unwrap(o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._sync() - _unwrap(o)

    def __rsub__(self, o):
        return _unwrap(o) - self._sync()

    def __mul__(self, o):
        return self._sync() * _unwrap(o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._sync() / _unwrap(o)

    def __rtruediv__(self, o):
        return _unwrap(o) / self._sync()

    def __neg__(self):
        return -self._sync()

    def __array__(self, dtype=None):
        return np.asarray(self._sync(), dtype=dtype)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step_fn = None
        self._eval_step_fn = None
        self._opt_state = None
        self.stop_training = False
        self._monitor = None
        self._mon_names = []
        self._mon_step = 0

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            assert isinstance(m, Metric), "metrics must be paddle metrics"
        self._amp = amp_configs or {}
        self._build_steps()
        return self

    def _build_steps(self):
        net = self.network
        loss_fn = self._loss
        opt = self._optimizer
        fwd = getattr(net, "_orig_forward", None)
        if fwd is None:
            fwd = net.forward
        from ..jit.api import functional_call, StaticFunction
        if isinstance(fwd, StaticFunction):
            fwd = fwd._orig_fn

        def grad_step(params, buffers, key, inputs, labels):
            def loss_of(p):
                with _random.trace_key_scope(key):
                    outs, new_buffers = functional_call(
                        net, p, buffers,
                        tuple(Tensor(x) for x in inputs),
                        training=True, forward_fn=fwd)
                outs = _to_list(outs)
                lbls = [Tensor(l) for l in labels]
                loss = loss_fn(*(outs + lbls))
                if isinstance(loss, (list, tuple)):
                    loss = loss[0]
                preds = [o._data for o in outs]
                return loss._data, (preds, new_buffers)

            (loss_v, (preds, new_buffers)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            return loss_v, preds, new_buffers, grads

        def apply_step(params, grads, opt_state):
            return opt.apply_gradients_tree(params, grads, opt_state)

        # numerics sentinel: like capture, the decision is baked at
        # build/trace time so health outputs compile into the same
        # program — a monitored fit never gains a second compile or a
        # per-step host sync
        from ..observability import numerics as _numerics
        mon = _numerics.get_monitor()
        mon = mon if mon.enabled else None
        self._monitor = mon
        self._mon_names = mon_box = []
        self._mon_step = 0

        def train_step(params, buffers, opt_state, key, inputs, labels):
            loss_v, preds, new_buffers, grads = grad_step(
                params, buffers, key, inputs, labels)
            new_params, new_opt_state = apply_step(params, grads, opt_state)
            if mon is None:
                return loss_v, preds, new_params, new_buffers, new_opt_state
            names, health = _numerics.health_outputs(
                grads, loss=loss_v, with_stats=mon.stats_on)
            mon_box[:] = [names]
            return (loss_v, preds, new_params, new_buffers, new_opt_state,
                    health)

        def apply_step_mon(params, grads, opt_state, loss_v):
            # split-path twin (tracer on): health rides on the
            # optimizer program, where the grads are already in hand
            new_params, new_opt_state = apply_step(params, grads, opt_state)
            names, health = _numerics.health_outputs(
                grads, loss=loss_v, with_stats=mon.stats_on)
            mon_box[:] = [names]
            return new_params, new_opt_state, health

        def eval_step(params, buffers, inputs, labels):
            outs, _ = functional_call(
                net, params, buffers, tuple(Tensor(x) for x in inputs),
                training=False, forward_fn=fwd)
            outs = _to_list(outs)
            loss_v = None
            # `labels` is a host-side list pytree: its truthiness is the
            # arity of the batch, static under tracing, not a tensor bool
            if loss_fn is not None and labels:  # tpu-lint: disable=TPU002
                lbls = [Tensor(l) for l in labels]
                loss = loss_fn(*(outs + lbls))
                if isinstance(loss, (list, tuple)):
                    loss = loss[0]
                loss_v = loss._data
            return loss_v, [o._data for o in outs]

        # One fused program per step is the perf contract; the split
        # grad/apply pair exists ONLY for the step-phase tracer, which
        # needs a host boundary between backward and optimizer to time.
        # jax.jit is lazy, so the untaken pair never compiles.  Each
        # step is run through the graph-level fusion pass at trace time
        # (transparent when PT_FUSION_PASS=0 or nothing matches).
        from ..ops import fusion_pass as _fusion
        self._train_step_jit = jax.jit(_fusion.wrap(train_step)) \
            if opt is not None else None
        self._grad_step_jit = jax.jit(_fusion.wrap(grad_step)) \
            if opt is not None else None
        self._apply_step_jit = jax.jit(
            apply_step_mon if mon is not None else apply_step) \
            if opt is not None else None
        self._eval_step_jit = jax.jit(_fusion.wrap(eval_step))

    def _param_arrays(self):
        return {k: p._data for k, p in self.network.named_parameters()}

    def _buffer_arrays(self):
        return {k: b._data for k, b in self.network.named_buffers()}

    def _write_back(self, params, buffers):
        named_p = dict(self.network.named_parameters())
        for k, v in params.items():
            named_p[k]._data = v
        named_b = dict(self.network.named_buffers())
        for k, v in buffers.items():
            named_b[k]._data = v

    # -- single-batch paths --------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        with autograd.functional_guard():
            params = self._param_arrays()
            buffers = self._buffer_arrays()
            if self._opt_state is None:
                self._opt_state = self._optimizer.init_state_tree(params)
            key = _random.next_key()
            tr = get_tracer()
            mon = self._monitor
            health = None
            try:
                if tr.enabled:
                    # split path: "backward" is the fused forward+backward
                    # value_and_grad program (no pure-forward phase exists
                    # in a train step), "optimizer" the parameter update.
                    # Spans time dispatch — never a forced device sync.
                    with tr.phase("backward"):
                        loss_v, preds, new_buffers, grads = \
                            self._grad_step_jit(
                                params, buffers, key,
                                _arrays(inputs), _arrays(labels))
                    with tr.phase("optimizer"):
                        if mon is not None:
                            new_params, new_opt, health = \
                                self._apply_step_jit(
                                    params, grads, self._opt_state, loss_v)
                        else:
                            new_params, new_opt = self._apply_step_jit(
                                params, grads, self._opt_state)
                elif mon is not None:
                    (loss_v, preds, new_params, new_buffers, new_opt,
                     health) = self._train_step_jit(
                        params, buffers, self._opt_state, key,
                        _arrays(inputs), _arrays(labels))
                else:
                    loss_v, preds, new_params, new_buffers, new_opt = \
                        self._train_step_jit(params, buffers,
                                             self._opt_state,
                                             key, _arrays(inputs),
                                             _arrays(labels))
            except Exception as e:
                self._book_oom("hapi.train_batch", e)
                raise
            if update:
                self._write_back(new_params, new_buffers)
                self._opt_state = new_opt
                if self._optimizer._learning_rate_scheduler is not None:
                    pass  # stepped per-epoch by callbacks/fit
            if mon is not None and health is not None and self._mon_names:
                # after the writeback so a PT_NUMERICS_HALT raise leaves
                # the model in the post-step state (same as capture)
                step_i = self._mon_step
                self._mon_step += 1
                mon.watch(step_i, self._mon_names[0], health)
        metrics_out = []
        for m in self._metrics:
            corr = m.compute(Tensor(preds[0]), Tensor(_arrays(labels)[0]))
            metrics_out.append(m.update(corr))
        # lazy: the step stays dispatched-but-unread until a callback or
        # caller actually looks at the number (LossScalar docstring)
        loss_out = [LossScalar(loss_v)]
        return (loss_out, metrics_out) if metrics_out else loss_out

    def _book_oom(self, program, exc):
        """RESOURCE_EXHAUSTED intercept for the hapi step paths: pin
        the memory postmortem (census attributed to this network's
        parameter paths) before the error propagates — same trip path
        as ``jit.capture``. Never raises; callers re-raise."""
        try:
            from ..observability import memory as _memory
            if not _memory.is_oom_error(exc):
                return
            named = {f"param::{k}": p._data
                     for k, p in self.network.named_parameters()}
            named.update({f"buffer::{k}": b._data
                          for k, b in self.network.named_buffers()})
            _memory.oom_postmortem(program=program, exc=exc,
                                   extra_named=named)
        except Exception:
            pass

    def eval_batch(self, inputs, labels=None):
        with autograd.functional_guard():
            try:
                with get_tracer().phase("forward"):
                    loss_v, preds = self._eval_step_jit(
                        self._param_arrays(), self._buffer_arrays(),
                        _arrays(inputs), _arrays(labels))
            except Exception as e:
                self._book_oom("hapi.eval_batch", e)
                raise
        metrics_out = []
        for m in self._metrics:
            corr = m.compute(Tensor(preds[0]), Tensor(_arrays(labels)[0]))
            metrics_out.append(m.update(corr))
        loss_out = [float(np.asarray(loss_v))] if loss_v is not None else []
        return (loss_out, metrics_out) if metrics_out else loss_out

    def predict_batch(self, inputs):
        with autograd.functional_guard():
            try:
                with get_tracer().phase("forward"):
                    _, preds = self._eval_step_jit(
                        self._param_arrays(), self._buffer_arrays(),
                        _arrays(inputs), [])
            except Exception as e:
                self._book_oom("hapi.predict_batch", e)
                raise
        return [Tensor(p) for p in preds]

    # -- loops ---------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader, Dataset
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data

        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            verbose=verbose,
            metrics=["loss"] + [n for m in self._metrics
                                for n in _to_list(m.name())])
        cbks.on_begin("train")
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            logs = self._run_one_epoch(train_loader, cbks, "train")
            if self._optimizer is not None and \
                    self._optimizer._learning_rate_scheduler is not None:
                self._optimizer._learning_rate_scheduler.step()
            # eval metrics merge BEFORE on_epoch_end so callbacks can
            # monitor eval_loss/eval_acc (ReduceLROnPlateau etc.)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
        cbks.on_end("train", logs)
        return self

    def _run_one_epoch(self, loader, cbks, mode):
        for m in self._metrics:
            m.reset()
        logs = {}
        tel = get_telemetry()
        for step, batch in enumerate(loader):
            batch = _to_list(batch)
            # convention: last element is the label set
            inputs, labels = batch[:-1], batch[-1:]
            if len(batch) == 1:
                inputs, labels = batch, []
            cbks.on_batch_begin(mode, step, logs)
            tok = tel.step_start()
            if mode == "train":
                out = self.train_batch(inputs, labels)
            else:
                out = self.eval_batch(inputs, labels)
            tel.step_end(tok, mode=mode,
                         batch_size=(np.shape(labels[0])[0]
                                     if labels else None))
            if isinstance(out, tuple):
                losses, metrics = out
            else:
                losses, metrics = out, []
            logs["loss"] = losses[0] if losses else None
            names = [n for m in self._metrics for n in _to_list(m.name())]
            for n, v in zip(names, metrics):
                # per-batch metric materialization is the callback
                # contract (on_batch_end receives floats, ref hapi)
                # tpu-lint: disable=TPU007
                logs[n] = float(np.asarray(v)) if not isinstance(v, list) \
                    else [float(x) for x in v]
            # np.shape reads metadata without copying device arrays to
            # host (np.asarray here forced a full transfer per batch)
            logs["batch_size"] = (np.shape(labels[0])[0]
                                  if labels else None)
            cbks.on_batch_end(mode, step, logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        from ..io import DataLoader, Dataset
        loader = DataLoader(eval_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(eval_data, Dataset) else eval_data
        for m in self._metrics:
            m.reset()
        total_loss, n = 0.0, 0
        tel = get_telemetry()
        for batch in loader:
            batch = _to_list(batch)
            inputs, labels = batch[:-1], batch[-1:]
            tok = tel.step_start()
            out = self.eval_batch(inputs, labels)
            tel.step_end(tok, mode="eval",
                         batch_size=(np.shape(labels[0])[0]
                                     if labels else None))
            losses = out[0] if isinstance(out, tuple) else out
            if losses:
                total_loss += losses[0]
                n += 1
        logs = {"loss": total_loss / max(n, 1)}
        for m in self._metrics:
            acc = m.accumulate()
            for name, v in zip(_to_list(m.name()), _to_list(acc)):
                logs[name] = v
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        from ..io import DataLoader, Dataset
        loader = DataLoader(test_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(test_data, Dataset) else test_data
        outputs = []
        for batch in loader:
            batch = _to_list(batch)
            preds = self.predict_batch(batch[:1])
            outputs.append([p.numpy() for p in preds])
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- io -----------------------------------------------------------------
    def save(self, path, training=True, sharded=False):
        from ..framework.io_state import save as _save
        if sharded:
            # distributed checkpoint: per-host shard files, reshardable on
            # load (ref: auto_parallel dist_saver)
            from ..distributed.checkpoint import save_sharded
            params = {k: t._data for k, t in
                      self.network.state_dict().items()}
            tree = {"params": params}
            if training and self._optimizer is not None:
                # hapi's compiled train step keeps optimizer state in
                # _opt_state (never the eager accumulators) — that tree
                # is the source of truth; zeros if training hasn't started
                tree["opt_tree"] = (
                    self._opt_state if self._opt_state is not None
                    else self._optimizer.init_state_tree(params))
            save_sharded(tree, path)
            return
        if training:
            _save(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                _save(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from ..jit import save as jit_save, InputSpec
            if self._inputs is None:
                raise ValueError("save(training=False) requires inputs= spec")
            jit_save(self.network, path, input_spec=self._inputs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io_state import load as _load
        if os.path.isdir(path):  # sharded checkpoint directory
            from ..distributed.checkpoint import load_sharded
            from ..distributed.checkpoint_manager import latest_checkpoint
            from ..tensor import Tensor
            # a CheckpointManager root (step_<n> subdirs) resolves to its
            # newest committed-and-valid step
            resolved = latest_checkpoint(path)
            if resolved is not None:
                path = resolved
            tree = load_sharded(path)
            self.network.set_state_dict(
                {k: Tensor(v) for k, v in tree["params"].items()})
            if not reset_optimizer and self._optimizer is not None and \
                    "opt_tree" in tree:
                ot = tree["opt_tree"]
                # empty subtrees (no master weights / slot-less SGD) have
                # no leaves to save — restore their containers
                ot.setdefault("slots", {})
                ot.setdefault("master", {})
                for s in self._optimizer._state_slots:
                    ot["slots"].setdefault(s, {})
                self._opt_state = ot
            return
        state = _load(path + ".pdparams") if os.path.exists(
            path + ".pdparams") else _load(path)
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)
