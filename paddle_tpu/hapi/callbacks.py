"""Training callbacks (ref: ``python/paddle/hapi/callbacks.py``)."""
from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "VisualDL", "config_callbacks", "CallbackList",
           "ReduceLROnPlateau", "WandbCallback"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin",
                lambda s, l=None: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end",
                lambda s, l=None: None)(step, logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def __iter__(self):
        return iter(self.callbacks)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def on_begin(self, mode, logs=None):
        self._call("on_begin", mode, logs)

    def on_end(self, mode, logs=None):
        self._call("on_end", mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call("on_batch_begin", mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call("on_batch_end", mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self._t0 = time.time()
        self.epoch = 0

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._steps = 0
        self._epoch_t0 = time.time()
        if self.verbose:
            total = self.params.get("epochs")
            print(f"Epoch {epoch + 1}/{total}")

    def on_train_batch_end(self, step, logs=None):
        self._steps += 1
        if self.verbose > 1 and step % self.log_freq == 0:
            items = [f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                     for k, v in (logs or {}).items()
                     if k in self.params.get("metrics", []) and v is not None]
            print(f"step {step}: " + ", ".join(items))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._epoch_t0
            items = [f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                     for k, v in (logs or {}).items()
                     if k != "batch_size" and v is not None]
            print(f"  {self._steps} steps in {dt:.1f}s - " + ", ".join(items))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return opt._learning_rate_scheduler if opt is not None else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step and self._sched() is not None:
            self._sched().step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch and self._sched() is not None:
            self._sched().step()


def _auto_mode(monitor, mode):
    if mode == "auto":
        return "max" if "acc" in monitor else "min"
    return mode


def _improved(v, best, mode, min_delta):
    if best is None:
        return True
    if mode == "min":
        return v < best - min_delta
    return v > best + min_delta


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        self.mode = _auto_mode(monitor, mode)

    def _better(self, v):
        return _improved(v, self.best, self.mode, self.min_delta)

    def on_epoch_end(self, epoch, logs=None):
        v = (logs or {}).get(self.monitor)
        if v is None:
            return
        if isinstance(v, (list, tuple)):
            v = v[0]
        if self._better(v):
            self.best = v
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class VisualDL(Callback):
    """Scalar logging to a simple jsonl (visualdl itself is not bundled)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        import json
        os.makedirs(self.log_dir, exist_ok=True)
        self._step += 1
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            # float(v) also catches hapi's lazy LossScalar (this logger
            # writes per batch, so the read — and the device sync it
            # implies — is this callback's own documented cost)
            f.write(json.dumps({"step": self._step,
                                **{k: float(v)
                                   for k, v in (logs or {}).items()
                                   if isinstance(v, (int, float))
                                   or hasattr(v, "__float__")}}) + "\n")


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    cbk_list.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or ["loss"],
    })
    return cbk_list


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer lr when the monitored metric plateaus (ref
    ``hapi/callbacks.py:1172``)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = float(factor)
        if self.factor >= 1.0:
            raise ValueError(
                "ReduceLROnPlateau does not support a factor >= 1.0")
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.mode = _auto_mode(monitor, mode)
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def _better(self, v):
        return _improved(v, self.best, self.mode, self.min_delta)

    def on_eval_end(self, logs=None):
        self._check(logs)

    def on_epoch_end(self, epoch, logs=None):
        self._check(logs)

    def _check(self, logs):
        logs = logs or {}
        v = logs.get(self.monitor)
        if v is None:
            return
        v = float(np.mean(v)) if np.ndim(v) else float(v)
        if self.cooldown_counter > 0:
            # patience must not advance while cooling down (Keras/ref
            # semantics) — but a genuine improvement still updates best
            self.cooldown_counter -= 1
            self.wait = 0
            if self._better(v):
                self.best = v
            return
        if self._better(v):
            self.best = v
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is None:
                return
            lr = float(opt.get_lr())
            new_lr = max(lr * self.factor, self.min_lr)
            if new_lr < lr:
                opt.set_lr(new_lr)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr {lr:.3e} -> {new_lr:.3e}")
            self.cooldown_counter = self.cooldown
            self.wait = 0


class WandbCallback(Callback):
    """Weights & Biases logger (ref ``hapi/callbacks.py:1345``):
    requires the ``wandb`` package at run time; metric logs forward to
    ``wandb.log`` with the reference's train/eval prefixes."""

    def __init__(self, project=None, entity=None, name=None, dir=None,
                 mode=None, job_type=None, **kwargs):
        super().__init__()
        try:
            import wandb
        except ImportError:
            raise ImportError(
                "WandbCallback requires the wandb package; install it "
                "with: pip install wandb")
        self.wandb = wandb
        self._owns_run = wandb.run is None
        self.run = wandb.init(project=project, entity=entity, name=name,
                              dir=dir, mode=mode, job_type=job_type,
                              **kwargs) if self._owns_run else wandb.run

    def _log(self, prefix, logs):
        logs = logs or {}
        payload = {f"{prefix}/{k}": (float(np.mean(v)) if np.ndim(v)
                                     else float(v))
                   for k, v in logs.items()
                   if isinstance(v, (int, float, list, tuple, np.ndarray))}
        if payload:
            self.run.log(payload)

    def on_train_batch_end(self, step, logs=None):
        self._log("train", logs)

    def on_eval_end(self, logs=None):
        self._log("eval", logs)

    def on_train_end(self, logs=None):
        if self._owns_run:  # never finish a run the user created
            self.run.finish()
