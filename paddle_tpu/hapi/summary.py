"""Model summary + FLOPs estimation (ref: ``python/paddle/hapi/
{model_summary,dynamic_flops}.py``)."""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["summary", "flops"]


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; returns {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def register(layer, name):
        def hook(l, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) \
                else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else "?"
            n_params = sum(p.size for p in l._parameters.values()
                           if p is not None)
            rows.append((name or type(l).__name__, type(l).__name__,
                         shape, n_params))
        hooks.append(layer.register_forward_post_hook(hook))

    for name, sub in net.named_sublayers(include_self=False):
        if not sub._sub_layers:  # leaves only
            register(sub, name)

    if input is not None:
        x = input
    else:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = input_size if isinstance(input_size, list) and isinstance(
            input_size[0], (list, tuple)) else [input_size]
        x = [Tensor(np.zeros([s if s is not None else 1 for s in size],
                             dtype=np.float32)) for size in sizes]
        x = x[0] if len(x) == 1 else x

    was_training = net.training
    net.eval()
    try:
        net(x) if not isinstance(x, list) else net(*x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(p.size for p in net.parameters())
    trainable = sum(p.size for p in net.parameters() if p.trainable)
    header = f"{'Layer':<40}{'Type':<24}{'Output Shape':<24}{'Params':>12}"
    print(header)
    print("-" * len(header))
    for name, typ, shape, n in rows:
        print(f"{name:<40}{typ:<24}{str(shape):<24}{n:>12,}")
    print("-" * len(header))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    return {"total_params": total, "trainable_params": trainable}


def flops(net: Layer, input_size, custom_ops=None, print_detail=False):
    """Estimate forward FLOPs by tracing to a jaxpr and costing the dot/conv
    ops — exact for the MXU-relevant operations (the reference hand-counts
    per layer type instead)."""
    import jax
    import jax.numpy as jnp
    from ..jit.api import functional_call

    x = jnp.zeros(input_size, dtype=jnp.float32)
    params = {k: p._data for k, p in net.named_parameters()}
    buffers = {k: b._data for k, b in net.named_buffers()}

    def pure(p, b, xx):
        out, _ = functional_call(net, p, b, (Tensor(xx),), training=False)
        return out._data if isinstance(out, Tensor) else out

    analysis = jax.jit(pure).lower(params, buffers, x).compile()
    try:
        cost = analysis.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        total = int(cost.get("flops", 0))
    except Exception:
        total = 0
    if print_detail:
        print(f"Total FLOPs: {total:,}")
    return total
