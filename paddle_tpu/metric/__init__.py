"""Metrics (ref: ``python/paddle/metric/metrics.py``)."""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing executed on device; defaults to identity."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        from ..ops.search import topk as _topk
        pred = pred if isinstance(pred, Tensor) else Tensor(pred)
        label = label if isinstance(label, Tensor) else Tensor(label)
        _, idx = _topk(pred, self.maxk, axis=-1)
        lab = np.asarray(label._data)
        if lab.ndim == idx.ndim:
            lab = lab[..., 0] if lab.shape[-1] == 1 else np.argmax(lab, -1)
        correct = (np.asarray(idx._data) == lab[..., None])
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(correct._data if isinstance(correct, Tensor)
                       else correct)
        num = c.shape[0] if c.ndim else 1
        for i, k in enumerate(self.topk):
            self.total[i] += c[..., :k].sum()
        self.count += num
        out = [t / max(self.count, 1) for t in self.total]
        return out[0] if len(out) == 1 else out

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        out = [t / max(self.count, 1) for t in self.total]
        return out[0] if len(out) == 1 else out

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor) else labels)
        pred_pos = (p > 0.5).astype(np.int64).ravel()
        l = l.ravel()
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fp += int(((pred_pos == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor) else labels)
        pred_pos = (p > 0.5).astype(np.int64).ravel()
        l = l.ravel()
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fn += int(((pred_pos == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Thresholded ROC AUC (ref: metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor)
                       else labels).ravel()
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.ravel()
        idx = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate from highest threshold down
        pos = self._stat_pos[::-1].cumsum()
        neg = self._stat_neg[::-1].cumsum()
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy (ref: ``paddle.metric.accuracy``)."""
    from ..ops.search import topk as _topk
    import jax.numpy as jnp
    input = input if isinstance(input, Tensor) else Tensor(input)
    label = label if isinstance(label, Tensor) else Tensor(label)
    _, idx = _topk(input, k, axis=-1)
    lab = label._data
    if lab.ndim == idx._data.ndim:
        lab = lab[..., 0]
    correct_ = (idx._data == lab[..., None]).any(axis=-1)
    return Tensor(jnp.mean(correct_.astype(jnp.float32)))
