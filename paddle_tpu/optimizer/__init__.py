"""``paddle_tpu.optimizer`` (ref: ``python/paddle/optimizer/__init__.py``)."""
from .optimizer import (Optimizer, SGD, Momentum, Adagrad, Adadelta,  # noqa: F401
                        RMSProp)
from .adam import Adam, AdamW, Adamax, Lamb, NAdam, RAdam  # noqa: F401
from .lbfgs import LBFGS  # noqa: F401
from . import lr  # noqa: F401
