"""Optimizer base + SGD family.

TPU-native re-design of the reference optimizer stack
(``python/paddle/optimizer/optimizer.py``; ``step`` at ``:1558`` dispatching
to fused CUDA kernels like ``_C_ops.adam_``):

 - every optimizer defines one pure function ``_update(p, g, state, lr,
   **hyper)`` over raw arrays. Eagerly it runs jitted-with-donation (the
   fused-kernel equivalent — XLA fuses the whole update into one kernel);
   under ``to_static`` training the same function is traced into the single
   train-step program.
 - master weights (fp32 copies for bf16/fp16 params) replace the reference's
   multi_precision machinery; enabled automatically for low-precision params.
"""
from __future__ import annotations

import functools
from collections import OrderedDict

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor, Parameter
from ..nn.clip import ClipGradBase
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta", "RMSProp"]


def _is_low_precision(dt):
    return np.dtype(dt) in (np.dtype(np.float16), jnp.bfloat16)


class Optimizer:
    """Base class (ref: optimizer.py Optimizer)."""

    # subclasses override: state slot names created per parameter
    _state_slots: tuple = ()
    _hyper: dict = {}

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=True):
        if parameters is None:
            from ..jit.api import in_dynamic_mode
            if in_dynamic_mode():
                raise ValueError(
                    "parameters must be given in dygraph mode "
                    "(pass model.parameters())")
            parameters = []  # static mode: minimize() finds params via graph
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if isinstance(weight_decay, float):
            self._weight_decay = weight_decay
            self._wd_mode = "l2"  # L2Decay: applied to grad
        elif weight_decay is not None:
            self._weight_decay = getattr(weight_decay, "_coeff",
                                         getattr(weight_decay, "coeff", 0.0))
            from ..regularizer import L1Decay
            self._wd_mode = "l1" if isinstance(weight_decay, L1Decay) else "l2"
        else:
            self._weight_decay = 0.0
            self._wd_mode = "l2"
        # per-param state: {slot_name: {param_name: array}}
        self._accumulators: dict = {s: {} for s in self._state_slots}
        self._master_weights: dict = {}
        self._global_step = 0
        self._update_jit = None

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the learning rate is a scheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- state ---------------------------------------------------------------
    def _ensure_state(self, p: Tensor):
        key = p.name
        for slot in self._state_slots:
            if key not in self._accumulators[slot]:
                self._accumulators[slot][key] = self._init_slot(slot, p)
        if self._multi_precision and _is_low_precision(p._data.dtype) and \
                key not in self._master_weights:
            self._master_weights[key] = p._data.astype(jnp.float32)

    def _init_slot(self, slot, p):
        return jnp.zeros_like(
            p._data, dtype=jnp.float32 if _is_low_precision(p._data.dtype)
            else p._data.dtype)

    # -- the pure update (override) ------------------------------------------
    @staticmethod
    def _update(p, g, state, lr, **hyper):
        """(param, grad, state tuple, lr) -> (new_param, new_state tuple).
        Computed in fp32 when a master weight is threaded as `p`."""
        raise NotImplementedError

    # set while jit.capture_step traces this optimizer: step() must run
    # the pure tree update over the THREADED state (tracer step counter,
    # runtime lr) — the eager per-param path would bake this trace's
    # global_step as a constant into the compiled program
    _capture_hook = None

    # -- eager step ----------------------------------------------------------
    def step(self):
        if self._capture_hook is not None:
            self._capture_hook(self)
            return
        self._global_step += 1
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        if self._update_jit is None:
            hyper = dict(self._hyper)
            cls = type(self)
            wd_mode = self._wd_mode

            # one jitted fused update, cached by XLA per (shape, dtype) —
            # the analog of the reference's fused adam/momentum CUDA kernels
            @functools.partial(jax.jit, donate_argnums=(0, 2))
            def upd(p, g, state, lr, wd, step, master):
                compute = master if master is not None else p
                g = g.astype(compute.dtype)
                if not cls._decoupled_wd:
                    # wd==0 is the common case; the extra fused multiply-add
                    # is free inside the XLA kernel
                    g = g + (wd * jnp.sign(compute) if wd_mode == "l1"
                             else wd * compute)
                new_p, new_state = cls._update(
                    compute, g, state, lr, step=step, **hyper)
                if cls._decoupled_wd:
                    new_p = new_p - lr * wd * compute
                if master is not None:
                    return new_p.astype(p.dtype), new_state, new_p
                return new_p, new_state, None
            self._update_jit = upd
        lr = self.get_lr()
        step_arr = jnp.int32(self._global_step)
        for p, g in params_grads:
            self._ensure_state(p)
            key = p.name
            state = tuple(self._accumulators[s][key]
                          for s in self._state_slots)
            master = self._master_weights.get(key)
            p_lr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                if isinstance(p, Parameter) else lr
            wd = self._param_weight_decay(p)
            new_p, new_state, new_master = self._update_jit(
                p._data, g._data, state, jnp.float32(p_lr), jnp.float32(wd),
                step_arr, master)
            p._data = new_p
            for s, v in zip(self._state_slots, new_state):
                self._accumulators[s][key] = v
            if new_master is not None:
                self._master_weights[key] = new_master

    def _param_weight_decay(self, p):
        reg = getattr(p, "regularizer", None)
        if reg is not None:
            return getattr(reg, "_coeff", getattr(reg, "coeff", 0.0))
        return self._weight_decay

    # False: L2 folded into grad (SGD/Momentum); True: decoupled (AdamW)
    _decoupled_wd = False

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static.graph import Variable as _StaticVar
        if isinstance(loss, _StaticVar):
            # static mode: record the fused backward+update node
            from ..static.gradients import append_minimize
            return append_minimize(self, loss, parameters=parameters)
        if loss._node is not None:
            loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameter_list]

    # -- functional API for jitted training steps ---------------------------
    def init_state_tree(self, params: dict):
        """params: {name: array} -> opt state pytree (for to_static/hapi)."""
        state = {s: {} for s in self._state_slots}
        master = {}
        for name, arr in params.items():
            for s in self._state_slots:
                state[s][name] = jnp.zeros_like(
                    arr, dtype=jnp.float32 if _is_low_precision(arr.dtype)
                    else arr.dtype)
            if self._multi_precision and _is_low_precision(arr.dtype):
                master[name] = arr.astype(jnp.float32)
        return {"slots": state, "master": master, "step": jnp.zeros((), jnp.int32)}

    def apply_gradients_tree(self, params: dict, grads: dict, state: dict,
                             lr=None):
        """Pure: (params, grads, state) -> (new_params, new_state).
        Traceable under jit; the whole tree updates in one XLA program."""
        lr = jnp.float32(self.get_lr() if lr is None else lr)
        step = state["step"] + 1
        new_params, new_slots, new_master = {}, {s: {} for s in
                                                 self._state_slots}, {}
        # grad clip over the whole tree
        if self._grad_clip is not None:
            names = list(grads)
            clipped = self._grad_clip.apply_arrays([grads[n] for n in names])
            grads = dict(zip(names, clipped))
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                for s in self._state_slots:
                    new_slots[s][name] = state["slots"][s][name]
                if name in state["master"]:
                    new_master[name] = state["master"][name]
                continue
            master = state["master"].get(name)
            compute = master if master is not None else p
            g = g.astype(compute.dtype)
            wd = self._weight_decay
            if wd and not self._decoupled_wd:
                g = g + (wd * jnp.sign(compute) if self._wd_mode == "l1"
                         else wd * compute)
            st = tuple(state["slots"][s][name] for s in self._state_slots)
            new_p, new_st = type(self)._update(compute, g, st, lr, step=step,
                                               **self._hyper)
            if wd and self._decoupled_wd:
                new_p = new_p - lr * wd * compute
            if master is not None:
                new_master[name] = new_p
                new_p = new_p.astype(p.dtype)
            new_params[name] = new_p
            for s, v in zip(self._state_slots, new_st):
                new_slots[s][name] = v
        return new_params, {"slots": new_slots, "master": new_master,
                            "step": step}

    # -- checkpointing -------------------------------------------------------
    def state_dict(self):
        out = {}
        for slot, d in self._accumulators.items():
            for pname, arr in d.items():
                out[f"{pname}_{slot}"] = Tensor(arr)
        for pname, arr in self._master_weights.items():
            out[f"{pname}_master"] = Tensor(arr)
        out["global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        state = dict(state)
        if "LR_Scheduler" in state and isinstance(self._learning_rate,
                                                  LRScheduler):
            self._learning_rate.set_state_dict(state.pop("LR_Scheduler"))
        self._global_step = int(state.pop("global_step", 0))
        for key, val in state.items():
            arr = val._data if isinstance(val, Tensor) else jnp.asarray(
                np.asarray(val))
            if key.endswith("_master"):
                self._master_weights[key[:-7]] = arr
                continue
            for slot in self._state_slots:
                suffix = f"_{slot}"
                if key.endswith(suffix):
                    self._accumulators[slot][key[:-len(suffix)]] = arr
                    break

    @property
    def _learning_rate_scheduler(self):
        return self._learning_rate if isinstance(self._learning_rate,
                                                 LRScheduler) else None


class SGD(Optimizer):
    _state_slots = ()

    @staticmethod
    def _update(p, g, state, lr, step=0):
        return p - lr * g, state


class Momentum(Optimizer):
    """ref: optimizer/momentum.py; use_nesterov supported."""

    _state_slots = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._hyper = {"momentum": momentum, "nesterov": use_nesterov}

    @staticmethod
    def _update(p, g, state, lr, step=0, momentum=0.9, nesterov=False):
        (v,) = state
        v_new = momentum * v + g
        if nesterov:
            p_new = p - lr * (g + momentum * v_new)
        else:
            p_new = p - lr * v_new
        return p_new, (v_new,)


class Adagrad(Optimizer):
    _state_slots = ("moment",)

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._hyper = {"epsilon": epsilon}
        self._initial_acc = initial_accumulator_value

    def _init_slot(self, slot, p):
        base = super()._init_slot(slot, p)
        return base + self._initial_acc

    @staticmethod
    def _update(p, g, state, lr, step=0, epsilon=1e-6):
        (m,) = state
        m_new = m + g * g
        return p - lr * g / (jnp.sqrt(m_new) + epsilon), (m_new,)


class Adadelta(Optimizer):
    _state_slots = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._hyper = {"epsilon": epsilon, "rho": rho}

    @staticmethod
    def _update(p, g, state, lr, step=0, epsilon=1e-6, rho=0.95):
        sg, su = state
        sg_new = rho * sg + (1 - rho) * g * g
        upd = jnp.sqrt(su + epsilon) / jnp.sqrt(sg_new + epsilon) * g
        su_new = rho * su + (1 - rho) * upd * upd
        return p - lr * upd, (sg_new, su_new)


class RMSProp(Optimizer):
    _state_slots = ("mean_square", "mean_grad", "momentum_acc")

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._hyper = {"rho": rho, "epsilon": epsilon, "momentum": momentum,
                       "centered": centered}

    @staticmethod
    def _update(p, g, state, lr, step=0, rho=0.95, epsilon=1e-6, momentum=0.0,
                centered=False):
        ms, mg, mom = state
        ms_new = rho * ms + (1 - rho) * g * g
        if centered:
            mg_new = rho * mg + (1 - rho) * g
            denom = jnp.sqrt(ms_new - mg_new * mg_new + epsilon)
        else:
            mg_new = mg
            denom = jnp.sqrt(ms_new + epsilon)
        mom_new = momentum * mom + lr * g / denom
        return p - mom_new, (ms_new, mg_new, mom_new)
