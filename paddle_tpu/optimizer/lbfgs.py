"""LBFGS optimizer (ref: ``python/paddle/optimizer/lbfgs.py``).

Limited-memory BFGS with optional strong-Wolfe line search, the
closure-style ``step(closure)`` API of the reference. The quasi-Newton
math runs on ONE flattened f32 vector on device (jnp) — history
dot-products and the two-loop recursion are a handful of fused
elementwise/reduction XLA ops, not per-parameter Python loops.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["LBFGS"]


def _flat(arrays):
    return jnp.concatenate([jnp.ravel(a).astype(jnp.float32)
                            for a in arrays])


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        if weight_decay is not None or grad_clip is not None:
            # decay/clip would make the line search's f and g inconsistent
            # (closure computes f without them); refuse loudly rather than
            # silently training unregularized
            raise NotImplementedError(
                "LBFGS does not support weight_decay/grad_clip: fold the "
                "penalty into the closure's loss instead")
        super().__init__(learning_rate=learning_rate, parameters=parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip,
                         name=name, multi_precision=False)
        if max_eval is None:
            max_eval = max_iter * 5 // 4
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError(
                f"only 'strong_wolfe' line search is supported, got "
                f"{line_search_fn!r}")
        self.max_iter = max_iter
        self.max_eval = max_eval
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._hist_s: list = []
        self._hist_y: list = []
        self._rho: list = []
        self._first_iter = True
        self._n_evals = 0
        self._last_loss_tensor = None

    # -- flat <-> param views ----------------------------------------------
    def _params(self):
        return [p for p in self._parameter_list if not p.stop_gradient]

    def _gather(self, attr):
        ps = self._params()
        if attr == "data":
            return _flat([p._data for p in ps])
        return _flat([(p.grad._data if p.grad is not None
                       else jnp.zeros_like(p._data)) for p in ps])

    def _scatter(self, flat):
        off = 0
        for p in self._params():
            n = int(np.prod(p.shape)) if p.shape else 1
            p._data = jnp.reshape(flat[off:off + n],
                                  p._data.shape).astype(p._data.dtype)
            off += n

    def _closure_eval(self, closure, x=None):
        if x is not None:
            self._scatter(x)
        self.clear_grad()
        loss = closure()
        self._last_loss_tensor = loss  # step() returns the Tensor (ref API)
        self._n_evals += 1
        return float(loss.item()), self._gather("grad")

    # -- two-loop recursion --------------------------------------------------
    def _direction(self, g):
        q = -g
        if not self._hist_s:
            return q
        alphas = []
        for s, y, rho in zip(reversed(self._hist_s),
                             reversed(self._hist_y),
                             reversed(self._rho)):
            a = rho * jnp.vdot(s, q)
            alphas.append(a)
            q = q - a * y
        s_last, y_last = self._hist_s[-1], self._hist_y[-1]
        gamma = jnp.vdot(s_last, y_last) / jnp.maximum(
            jnp.vdot(y_last, y_last), 1e-20)
        q = q * gamma
        for (s, y, rho), a in zip(zip(self._hist_s, self._hist_y,
                                      self._rho), reversed(alphas)):
            b = rho * jnp.vdot(y, q)
            q = q + s * (a - b)
        return q

    def _push_history(self, s, y):
        ys = float(jnp.vdot(y, s))
        if ys > 1e-10:
            self._hist_s.append(s)
            self._hist_y.append(y)
            self._rho.append(1.0 / ys)
            if len(self._hist_s) > self.history_size:
                self._hist_s.pop(0)
                self._hist_y.pop(0)
                self._rho.pop(0)

    # -- strong-Wolfe line search (cubic interpolation, torch/paddle algo) --
    def _strong_wolfe(self, closure, x0, d, f0, g0, t, c1=1e-4, c2=0.9,
                      max_ls=25):
        dg0 = float(jnp.vdot(g0, d))
        if dg0 >= 0:  # not a descent direction; bail with no move
            return f0, g0, 0.0

        def phi(t_):
            f, g = self._closure_eval(closure, x0 + t_ * d)
            return f, g, float(jnp.vdot(g, d))

        # bracket phase
        t_prev, f_prev, dg_prev = 0.0, f0, dg0
        g_prev = g0
        bracket = None
        for _ in range(max_ls):
            f_new, g_new, dg_new = phi(t)
            if f_new > f0 + c1 * t * dg0 or f_new >= f_prev:
                bracket = (t_prev, t, f_prev, f_new, g_prev, g_new,
                           dg_prev, dg_new)
                break
            if abs(dg_new) <= -c2 * dg0:
                return f_new, g_new, t
            if dg_new >= 0:
                bracket = (t, t_prev, f_new, f_prev, g_new, g_prev,
                           dg_new, dg_prev)
                break
            t_prev, f_prev, g_prev, dg_prev = t, f_new, g_new, dg_new
            t = t * 2.0
        else:
            # exhausted: return the LAST EVALUATED point (t was doubled
            # after phi ran; returning the doubled t would pair a step
            # with a loss/grad measured elsewhere)
            return f_new, g_new, t_prev

        # zoom phase
        lo, hi, f_lo, f_hi, g_lo, g_hi, dg_lo, dg_hi = bracket
        for _ in range(max_ls):
            if abs(hi - lo) * abs(dg0) < self.tolerance_change:
                break
            t = 0.5 * (lo + hi)  # bisection (cubic adds little here)
            f_new, g_new, dg_new = phi(t)
            if f_new > f0 + c1 * t * dg0 or f_new >= f_lo:
                hi, f_hi, g_hi, dg_hi = t, f_new, g_new, dg_new
            else:
                if abs(dg_new) <= -c2 * dg0:
                    return f_new, g_new, t
                if dg_new * (hi - lo) >= 0:
                    hi, f_hi, g_hi, dg_hi = lo, f_lo, g_lo, dg_lo
                lo, f_lo, g_lo, dg_lo = t, f_new, g_new, dg_new
        return f_lo, g_lo, lo

    # -- the closure-driven step --------------------------------------------
    def step(self, closure=None):
        """One LBFGS optimization pass (up to ``max_iter`` inner
        iterations). ``closure`` re-evaluates the loss and its gradients
        (call ``loss.backward()`` inside, like the reference)."""
        if closure is None:
            raise ValueError("LBFGS.step requires a closure")
        self._n_evals = 0
        lr = self.get_lr()

        loss, flat_grad = self._closure_eval(closure)
        # the reference returns the PRE-step loss (the first closure
        # evaluation), not whatever trial point the line search last saw
        orig_loss = self._last_loss_tensor
        if float(jnp.abs(flat_grad).max()) <= self.tolerance_grad:
            return orig_loss

        x = self._gather("data")
        for _ in range(self.max_iter):
            d = self._direction(flat_grad)
            if self._first_iter:
                t = min(1.0, 1.0 / max(float(jnp.abs(flat_grad).sum()),
                                       1e-10)) * lr
                self._first_iter = False
            else:
                t = lr

            if self.line_search_fn == "strong_wolfe":
                f_new, g_new, t = self._strong_wolfe(
                    closure, x, d, loss, flat_grad, t)
                x_new = x + t * d
                self._scatter(x_new)
            else:
                x_new = x + t * d
                f_new, g_new = self._closure_eval(closure, x_new)

            self._push_history(x_new - x, g_new - flat_grad)
            delta_x = float(jnp.abs(x_new - x).max()) if t != 0 else 0.0
            delta_f = abs(f_new - loss)
            x, loss, flat_grad = x_new, f_new, g_new

            if float(jnp.abs(flat_grad).max()) <= self.tolerance_grad:
                break
            if t == 0.0 or delta_x <= self.tolerance_change \
                    or delta_f <= self.tolerance_change:
                break
            if self._n_evals >= self.max_eval:
                break
        self._scatter(x)
        return orig_loss

    def state_dict(self):
        out = super().state_dict()
        out["lbfgs"] = {
            "hist_s": [np.asarray(s) for s in self._hist_s],
            "hist_y": [np.asarray(y) for y in self._hist_y],
            "rho": list(self._rho),
            "first_iter": self._first_iter,
        }
        return out

    def set_state_dict(self, state):
        if isinstance(state, dict):
            state = dict(state)  # caller's dict stays unmutated
            lb = state.pop("lbfgs", {})  # base would jnp.asarray() it
        else:
            lb = {}
        super().set_state_dict(state)
        self._hist_s = [jnp.asarray(s) for s in lb.get("hist_s", [])]
        self._hist_y = [jnp.asarray(y) for y in lb.get("hist_y", [])]
        self._rho = list(lb.get("rho", []))
        self._first_iter = bool(lb.get("first_iter", True))
