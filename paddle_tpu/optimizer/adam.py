"""Adam family (ref: ``python/paddle/optimizer/{adam,adamw,adamax,lamb}.py``).

The reference dispatches to fused CUDA kernels (``_C_ops.adam_``,
``multi_tensor_adam``); here the pure `_update` compiles to one fused XLA
kernel per parameter — and inside a jitted train step, the whole parameter
tree updates in a single program with no per-tensor launch overhead at all.
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["Adam", "AdamW", "Adamax", "Lamb", "NAdam", "RAdam"]


class Adam(Optimizer):
    _state_slots = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=True,
                 use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._hyper = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon,
                       "amsgrad": amsgrad}
        if amsgrad:
            # instance-level override (never mutate the class attribute)
            self._state_slots = ("moment1", "moment2", "moment2_max")
            self._accumulators = {s: {} for s in self._state_slots}

    @staticmethod
    def _update(p, g, state, lr, step=1, beta1=0.9, beta2=0.999,
                epsilon=1e-8, amsgrad=False):
        m1, m2 = state[0], state[1]
        t = jnp.maximum(step, 1).astype(jnp.float32)
        m1_new = beta1 * m1 + (1 - beta1) * g
        m2_new = beta2 * m2 + (1 - beta2) * g * g
        bc1 = 1 - beta1 ** t
        bc2 = 1 - beta2 ** t
        m1_hat = m1_new / bc1
        if amsgrad:
            m2_max = jnp.maximum(state[2], m2_new)
            m2_hat = m2_max / bc2
            new_state = (m1_new, m2_new, m2_max)
        else:
            m2_hat = m2_new / bc2
            new_state = (m1_new, m2_new)
        return p - lr * m1_hat / (jnp.sqrt(m2_hat) + epsilon), new_state


class AdamW(Adam):
    """Decoupled weight decay (default coeff 0.01 like the reference)."""

    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=True, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, name=name)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _param_weight_decay(self, p):
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            return 0.0
        return super()._param_weight_decay(p)


class Adamax(Optimizer):
    _state_slots = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._hyper = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon}

    @staticmethod
    def _update(p, g, state, lr, step=1, beta1=0.9, beta2=0.999,
                epsilon=1e-8):
        m, u = state
        t = jnp.maximum(step, 1).astype(jnp.float32)
        m_new = beta1 * m + (1 - beta1) * g
        u_new = jnp.maximum(beta2 * u, jnp.abs(g))
        bc1 = 1 - beta1 ** t
        return p - lr / bc1 * m_new / (u_new + epsilon), (m_new, u_new)


class Lamb(Optimizer):
    """Layer-wise adaptive moments (ref: optimizer/lamb.py) — the
    large-batch optimizer; trust ratio per parameter tensor."""

    _state_slots = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._hyper = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon,
                       "lamb_wd": lamb_weight_decay}
        self._exclude_fn = exclude_from_weight_decay_fn

    @staticmethod
    def _update(p, g, state, lr, step=1, beta1=0.9, beta2=0.999,
                epsilon=1e-6, lamb_wd=0.01):
        m1, m2 = state
        t = jnp.maximum(step, 1).astype(jnp.float32)
        m1_new = beta1 * m1 + (1 - beta1) * g
        m2_new = beta2 * m2 + (1 - beta2) * g * g
        m1_hat = m1_new / (1 - beta1 ** t)
        m2_hat = m2_new / (1 - beta2 ** t)
        r = m1_hat / (jnp.sqrt(m2_hat) + epsilon) + lamb_wd * p
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, (m1_new, m2_new)


class NAdam(Optimizer):
    _state_slots = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=True,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._hyper = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon,
                       "psi": momentum_decay}

    @staticmethod
    def _update(p, g, state, lr, step=1, beta1=0.9, beta2=0.999,
                epsilon=1e-8, psi=0.004):
        m1, m2 = state
        t = jnp.maximum(step, 1).astype(jnp.float32)
        mu_t = beta1 * (1 - 0.5 * 0.96 ** (t * psi))
        mu_t1 = beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * psi))
        m1_new = beta1 * m1 + (1 - beta1) * g
        m2_new = beta2 * m2 + (1 - beta2) * g * g
        m1_hat = mu_t1 * m1_new / (1 - mu_t * mu_t1) + \
            (1 - mu_t) * g / (1 - mu_t)
        m2_hat = m2_new / (1 - beta2 ** t)
        return p - lr * m1_hat / (jnp.sqrt(m2_hat) + epsilon), \
            (m1_new, m2_new)


class RAdam(Optimizer):
    _state_slots = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._hyper = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon}

    @staticmethod
    def _update(p, g, state, lr, step=1, beta1=0.9, beta2=0.999,
                epsilon=1e-8):
        m1, m2 = state
        t = jnp.maximum(step, 1).astype(jnp.float32)
        rho_inf = 2.0 / (1 - beta2) - 1
        m1_new = beta1 * m1 + (1 - beta1) * g
        m2_new = beta2 * m2 + (1 - beta2) * g * g
        bc1 = 1 - beta1 ** t
        bc2 = 1 - beta2 ** t
        rho_t = rho_inf - 2 * t * (beta2 ** t) / bc2
        m1_hat = m1_new / bc1
        r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf) /
                     jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-8))
        adaptive = r * m1_hat / (jnp.sqrt(m2_new / bc2) + epsilon)
        sgd_like = m1_hat
        return p - lr * jnp.where(rho_t > 5.0, adaptive, sgd_like), \
            (m1_new, m2_new)
