"""Functional autograd extras (ref: ``python/paddle/incubate/autograd/``
``primapi.py:25 forward_grad, :108 grad``). On TPU these map directly to
jax transforms — the reference's prim/composite decomposition machinery
(``paddle/fluid/prim/``) is XLA's job."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor import Tensor

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "forward_grad", "grad",
           "enable_prim", "disable_prim"]

# primitive-mode toggles (ref incubate/autograd/primapi.py): the
# reference lowers ops to primitive ops for higher-order AD; jax traces
# are already primitive-level, so the switch only records intent
_prim_enabled = False


def enable_prim():
    global _prim_enabled
    _prim_enabled = True


def disable_prim():
    global _prim_enabled
    _prim_enabled = False


def _fn_over_arrays(func):
    def f(*arrays):
        out = func(*[Tensor(a, stop_gradient=False) for a in arrays])
        return jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))
    return f


def jvp(func, xs, v=None):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [x._data if isinstance(x, Tensor) else jnp.asarray(x)
              for x in xs]
    if v is None:
        v = [jnp.ones_like(a) for a in arrays]
    else:
        v = [t._data if isinstance(t, Tensor) else jnp.asarray(t)
             for t in (v if isinstance(v, (list, tuple)) else [v])]
    out, tangent = jax.jvp(_fn_over_arrays(func), tuple(arrays), tuple(v))
    wrap = lambda tr: jax.tree_util.tree_map(Tensor, tr)
    return wrap(out), wrap(tangent)


def vjp(func, xs, v=None):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [x._data if isinstance(x, Tensor) else jnp.asarray(x)
              for x in xs]
    out, vjp_fn = jax.vjp(_fn_over_arrays(func), *arrays)
    if v is None:
        v = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v = jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else jnp.asarray(t),
            v, is_leaf=lambda t: isinstance(t, Tensor))
    grads = vjp_fn(v)
    wrap = lambda tr: jax.tree_util.tree_map(Tensor, tr)
    return wrap(out), list(wrap(grads))


forward_grad = jvp
grad = vjp


class Jacobian:
    """ref: primapi Jacobian — full dense jacobian, computed with jacrev."""

    def __init__(self, func, xs, is_batched=False):
        arrays = [x._data if isinstance(x, Tensor) else jnp.asarray(x)
                  for x in (xs if isinstance(xs, (list, tuple)) else [xs])]
        self._jac = jax.jacrev(_fn_over_arrays(func),
                               argnums=tuple(range(len(arrays))))(*arrays)

    def __getitem__(self, idx):
        return Tensor(jnp.asarray(self._jac[idx]))

    @property
    def value(self):
        return jax.tree_util.tree_map(Tensor, self._jac)


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        arrays = [x._data if isinstance(x, Tensor) else jnp.asarray(x)
                  for x in (xs if isinstance(xs, (list, tuple)) else [xs])]
        self._hes = jax.hessian(_fn_over_arrays(func),
                                argnums=tuple(range(len(arrays))))(*arrays)

    @property
    def value(self):
        return jax.tree_util.tree_map(Tensor, self._hes)
