"""Incubate optimizers (ref: ``python/paddle/incubate/optimizer/``)."""
from .._optimizer_impl import *  # noqa: F401,F403
from .._optimizer_impl import __all__ as _impl_all
from ...optimizer.lbfgs import LBFGS  # noqa: F401
from . import functional  # noqa: F401

__all__ = list(_impl_all) + ["LBFGS", "functional"]
