"""Functional quasi-Newton minimizers (ref:
``python/paddle/incubate/optimizer/functional/{bfgs,lbfgs}.py``).

Host-driven loops over jax value_and_grad with an Armijo backtracking
line search (the reference defaults to strong-wolfe; the return
contract — converged flag, call count, position, value, gradient
[, inverse hessian] — is identical).
"""
from __future__ import annotations

import numpy as np

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _prep(objective_func, initial_position, dtype):
    import jax
    import jax.numpy as jnp
    from ....tensor import Tensor

    def scalar_f(x):
        out = objective_func(Tensor(x))
        return (out._data if isinstance(out, Tensor) else
                jnp.asarray(out)).astype(jnp.float32).reshape(())

    x0 = jnp.asarray(
        initial_position._data if isinstance(initial_position, Tensor)
        else np.asarray(initial_position)).astype(dtype).ravel()
    return jax.jit(jax.value_and_grad(scalar_f)), x0


def _line_search(vg, x, d, f0, g0, max_iters, t0):
    """Weak-Wolfe line search (Armijo backtracking + curvature-driven
    extension — L-BFGS needs usable curvature pairs); returns
    (t, f, g, n_calls)."""
    import jax.numpy as jnp
    slope = float(jnp.vdot(g0, d))
    t, calls = float(t0), 0
    f, g = f0, g0
    for _ in range(max_iters):
        f, g = vg(x + t * d)
        calls += 1
        if float(f) <= float(f0) + 1e-4 * t * slope:
            break
        t *= 0.5
    # curvature (weak Wolfe): grow t while it helps and Armijo holds
    for _ in range(4):
        if float(jnp.vdot(g, d)) >= 0.9 * slope:
            break
        f2, g2 = vg(x + 2 * t * d)
        calls += 1
        if float(f2) <= float(f0) + 1e-4 * 2 * t * slope and \
                float(f2) < float(f):
            t, f, g = 2 * t, f2, g2
        else:
            break
    return t, f, g, calls


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe",
                  max_line_search_iters=50, initial_step_length=1.0,
                  dtype="float32", name=None):
    """ref ``bfgs.py:27``. Returns (is_converge, num_func_calls,
    position, objective_value, objective_gradient,
    inverse_hessian_estimate)."""
    import jax.numpy as jnp
    from ....tensor import Tensor
    vg, x = _prep(objective_func, initial_position, dtype)
    n = x.shape[0]
    H = jnp.eye(n, dtype=x.dtype) if initial_inverse_hessian_estimate \
        is None else jnp.asarray(
            initial_inverse_hessian_estimate._data
            if isinstance(initial_inverse_hessian_estimate, Tensor)
            else initial_inverse_hessian_estimate).astype(x.dtype)
    f, g = vg(x)
    calls = 1
    converged = False
    for _ in range(int(max_iters)):
        if float(jnp.abs(g).max()) <= tolerance_grad:
            converged = True
            break
        d = -(H @ g)
        t, f_new, g_new, c = _line_search(
            vg, x, d, f, g, max_line_search_iters, initial_step_length)
        calls += c
        s = t * d
        y = g_new - g
        if float(jnp.abs(s).max()) <= tolerance_change:
            x, f, g = x + s, f_new, g_new
            converged = True
            break
        sy = float(jnp.vdot(s, y))
        if sy > 1e-10:
            rho = 1.0 / sy
            I = jnp.eye(n, dtype=x.dtype)
            V = I - rho * jnp.outer(s, y)
            H = V @ H @ V.T + rho * jnp.outer(s, s)
        x, f, g = x + s, f_new, g_new
    shp = tuple(np.asarray(
        initial_position._data if isinstance(initial_position, Tensor)
        else initial_position).shape)
    return (converged, calls, Tensor(x.reshape(shp)), Tensor(f),
            Tensor(g.reshape(shp)), Tensor(H))


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-7,
                   tolerance_change=1e-9,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe",
                   max_line_search_iters=50, initial_step_length=1.0,
                   dtype="float32", name=None):
    """ref ``lbfgs.py:27``. Returns (is_converge, num_func_calls,
    position, objective_value, objective_gradient)."""
    import jax.numpy as jnp
    from ....tensor import Tensor
    vg, x = _prep(objective_func, initial_position, dtype)
    f, g = vg(x)
    calls = 1
    hist_s, hist_y = [], []
    converged = False
    for _ in range(int(max_iters)):
        if float(jnp.abs(g).max()) <= tolerance_grad:
            converged = True
            break
        # two-loop recursion
        q = g
        alphas = []
        for s, y in reversed(list(zip(hist_s, hist_y))):
            rho = 1.0 / float(jnp.vdot(s, y))
            a = rho * float(jnp.vdot(s, q))
            alphas.append((a, rho, s, y))
            q = q - a * y
        gamma = 1.0
        if hist_s:
            s, y = hist_s[-1], hist_y[-1]
            gamma = float(jnp.vdot(s, y)) / max(float(jnp.vdot(y, y)),
                                                1e-12)
        r = gamma * q
        for a, rho, s, y in reversed(alphas):
            b = rho * float(jnp.vdot(y, r))
            r = r + (a - b) * s
        d = -r
        t, f_new, g_new, c = _line_search(
            vg, x, d, f, g, max_line_search_iters, initial_step_length)
        calls += c
        s_vec = t * d
        y_vec = g_new - g
        if float(jnp.abs(s_vec).max()) <= tolerance_change:
            x, f, g = x + s_vec, f_new, g_new
            converged = True
            break
        if float(jnp.vdot(s_vec, y_vec)) > 1e-10:
            hist_s.append(s_vec)
            hist_y.append(y_vec)
            if len(hist_s) > history_size:
                hist_s.pop(0)
                hist_y.pop(0)
        x, f, g = x + s_vec, f_new, g_new
    shp = tuple(np.asarray(
        initial_position._data if isinstance(initial_position, Tensor)
        else initial_position).shape)
    return (converged, calls, Tensor(x.reshape(shp)), Tensor(f),
            Tensor(g.reshape(shp)))
