"""``paddle.incubate.autotune`` parity (ref:
``python/paddle/incubate/autotune.py`` set_config →
``paddle/phi/kernels/autotune/``).

``set_config({"kernel": {"enable": True}})`` switches the kernel-config
autotune cache on; :func:`tune_flash_attention` is the warmup tuner for
the Pallas flash-attention block sizes (timing must happen eagerly —
see ``ops/autotune.py``). The cache can be persisted/restored like the
reference's autotune cache file.
"""
from __future__ import annotations

import json

from ..ops import autotune as _at
from ..ops.pallas_ops import tune_mha

__all__ = ["set_config", "tune_flash_attention", "save_cache",
           "load_cache"]


def set_config(config=None):
    """config: dict or path to a JSON file, reference schema:
    ``{"kernel": {"enable": bool}, ...}`` (dataloader/layout sections are
    accepted and inert — XLA owns layout on TPU)."""
    if config is None:
        _at.set_enabled(True)
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    kcfg = config.get("kernel", {})
    _at.set_enabled(bool(kcfg.get("enable", False)))


def tune_flash_attention(query, key, value, *, causal=False,
                         interpret=None):
    """Eagerly time flash-attention block configs for these shapes and
    cache the winner (picked up by all subsequent calls, traced or not).
    Accepts Tensors or arrays in paddle (B, S, H, D) layout. Returns
    (best_config, timings)."""
    import jax.numpy as jnp
    from ..tensor import Tensor

    def arr(x):
        return x._data if isinstance(x, Tensor) else jnp.asarray(x)

    q = jnp.swapaxes(arr(query), 1, 2)
    k = jnp.swapaxes(arr(key), 1, 2)
    v = jnp.swapaxes(arr(value), 1, 2)
    return tune_mha(q, k, v, causal=causal, interpret=interpret)


save_cache = _at.save_cache
load_cache = _at.load_cache
