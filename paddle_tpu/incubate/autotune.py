"""``paddle.incubate.autotune`` parity (ref:
``python/paddle/incubate/autotune.py`` set_config →
``paddle/phi/kernels/autotune/``).

``set_config({"kernel": {"enable": True}})`` switches the kernel-config
autotune cache on; :func:`tune_flash_attention` is the warmup tuner for
the Pallas flash-attention block sizes (timing must happen eagerly —
see ``ops/autotune.py``). The cache can be persisted/restored like the
reference's autotune cache file.
"""
from __future__ import annotations

import json

from ..ops import autotune as _at
from ..ops.pallas_ops import tune_mha

__all__ = ["set_config", "tune_flash_attention", "tune_layer_norm",
           "tune_softmax_cross_entropy", "save_cache", "load_cache"]


def set_config(config=None):
    """config: dict or path to a JSON file, reference schema:
    ``{"kernel": {"enable": bool}, ...}`` (dataloader/layout sections are
    accepted and inert — XLA owns layout on TPU)."""
    if config is None:
        _at.set_enabled(True)
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    kcfg = config.get("kernel", {})
    _at.set_enabled(bool(kcfg.get("enable", False)))


def _arr(x):
    import jax.numpy as jnp
    from ..tensor import Tensor
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def tune_flash_attention(query, key, value, *, causal=False,
                         interpret=None):
    """Eagerly search flash-attention block configs for these shapes and
    cache the winner (picked up by all subsequent calls, traced or not).
    Accepts Tensors or arrays in paddle (B, S, H, D) layout. Returns
    (best_config, timings)."""
    import jax.numpy as jnp

    q = jnp.swapaxes(_arr(query), 1, 2)
    k = jnp.swapaxes(_arr(key), 1, 2)
    v = jnp.swapaxes(_arr(value), 1, 2)
    return tune_mha(q, k, v, causal=causal, interpret=interpret)


def tune_layer_norm(x, weight=None, bias=None, *, epsilon=1e-5,
                    interpret=None):
    """Warmup search for the fused layernorm launch config; ``x`` is the
    (rows, d) view the hot path will see (flatten leading dims first).
    Returns (best_config, timings)."""
    from ..ops.fused_kernels import tune_layer_norm as _tune
    return _tune(_arr(x),
                 None if weight is None else _arr(weight),
                 None if bias is None else _arr(bias),
                 epsilon=epsilon, interpret=interpret)


def tune_softmax_cross_entropy(logits, labels, *, ignore_index=-100,
                               label_smoothing=0.0, interpret=None):
    """Warmup search for the fused softmax-cross-entropy launch config
    at this (rows, V) logits shape. Returns (best_config, timings)."""
    from ..ops.fused_kernels import tune_softmax_xent as _tune
    return _tune(_arr(logits), _arr(labels), ignore_index=ignore_index,
                 label_smoothing=label_smoothing, interpret=interpret)


save_cache = _at.save_cache
load_cache = _at.load_cache
