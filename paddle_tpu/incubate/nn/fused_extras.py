"""Remaining fused layers (ref: ``python/paddle/incubate/nn/layer/
{fused_linear,fused_dropout_add,fused_ec_moe,fused_transformer}.py``).

"Fused" on TPU = one XLA fusion region: each layer is a single jnp
composition the compiler fuses, replacing the reference's hand-written
CUDA fusion kernels (``paddle/phi/kernels/fusion/``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...nn import Layer, functional as F
from ...ops.op_utils import ensure_tensor, nary
from ...framework import random as _random

__all__ = ["FusedLinear", "FusedDropoutAdd", "FusedEcMoe",
           "FusedBiasDropoutResidualLayerNorm"]


class FusedLinear(Layer):
    """Linear whose matmul+bias lower as one fused op (ref
    ``fused_linear.py:19``); ``transpose_weight`` stores W^T."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = [out_features, in_features] if transpose_weight else \
            [in_features, out_features]
        self.weight = self.create_parameter(shape=shape, attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        args = [ensure_tensor(x), self.weight]
        if self.bias is not None:
            args.append(self.bias)
        tw = self.transpose_weight

        def f(xd, wd, *rest):
            w = wd.T if tw else wd
            y = xd @ w
            return y + rest[0] if rest else y
        return nary(f, args, name="fused_linear")


class FusedDropoutAdd(Layer):
    """dropout(x) + y in one region (ref ``fused_dropout_add.py:19``)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        if mode not in ("upscale_in_train", "downscale_in_infer"):
            raise ValueError(f"mode {mode!r} is not supported")
        self.p = float(p)
        self.mode = mode

    def forward(self, x, y):
        x, y = ensure_tensor(x), ensure_tensor(y)
        if self.p == 0.0 or not self.training:
            if self.mode == "downscale_in_infer" and not self.training:
                return nary(lambda a, b: a * (1 - self.p) + b, [x, y],
                            name="fused_dropout_add")
            return nary(lambda a, b: a + b, [x, y],
                        name="fused_dropout_add")
        key = _random.next_key()

        def f(a, b):
            keep = jax.random.bernoulli(key, 1.0 - self.p, a.shape)
            scale = 1.0 / (1.0 - self.p) if \
                self.mode == "upscale_in_train" else 1.0
            return jnp.where(keep, a * scale, 0.0).astype(a.dtype) + b
        return nary(f, [x, y], name="fused_dropout_add")

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedEcMoe(Layer):
    """Expert-choice MoE FFN with stacked expert weights — the whole
    gate-softmax + two batched matmuls run as one region (ref
    ``fused_ec_moe.py:19``)."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if act_type not in ("gelu", "relu"):
            raise ValueError("act_type must be 'gelu' or 'relu'")
        self.act_type = act_type
        self.bmm_weight0 = self.create_parameter(
            shape=[num_experts, hidden_size, inter_size], attr=weight_attr)
        self.bmm_bias0 = self.create_parameter(
            shape=[num_experts, 1, inter_size], attr=bias_attr,
            is_bias=True)
        self.bmm_weight1 = self.create_parameter(
            shape=[num_experts, inter_size, hidden_size], attr=weight_attr)
        self.bmm_bias1 = self.create_parameter(
            shape=[num_experts, 1, hidden_size], attr=bias_attr,
            is_bias=True)

    def forward(self, x, gate):
        act = jax.nn.gelu if self.act_type == "gelu" else jax.nn.relu

        def f(xd, gd, w0, b0, w1, b1):
            # xd: (B, S, H); gd: (B, S, E) gate logits
            probs = jax.nn.softmax(gd.astype(jnp.float32), axis=-1) \
                .astype(xd.dtype)
            h = jnp.einsum("bsh,ehi->besi", xd, w0) + b0[None]
            h = act(h)
            o = jnp.einsum("besi,eih->besh", h, w1) + b1[None]
            return jnp.einsum("besh,bse->bsh", o, probs)
        return nary(f, [ensure_tensor(x), ensure_tensor(gate),
                        self.bmm_weight0, self.bmm_bias0,
                        self.bmm_weight1, self.bmm_bias1],
                    name="fused_ec_moe")


class FusedBiasDropoutResidualLayerNorm(Layer):
    """layer_norm(residual + dropout(x + bias)) in one region (ref
    ``fused_transformer.py:83``)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        if embed_dim <= 0:
            raise ValueError("embed_dim must be positive")
        self.embed_dim = embed_dim
        self.dropout_rate = float(dropout_rate)
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter(
            shape=[embed_dim], attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            shape=[embed_dim], attr=weight_attr, default_initializer=None)
        import numpy as np
        self.ln_scale.set_value(np.ones([embed_dim], np.float32))
        self.ln_bias = self.create_parameter(
            shape=[embed_dim], attr=bias_attr, is_bias=True)

    def forward(self, x, residual):
        p = self.dropout_rate if self.training else 0.0
        key = _random.next_key() if p > 0 else None
        eps = self._epsilon

        def f(xd, rd, b, g, lb):
            h = xd + b
            if key is not None:
                keep = jax.random.bernoulli(key, 1.0 - p, h.shape)
                h = jnp.where(keep, h / (1.0 - p), 0.0).astype(h.dtype)
            h = rd + h
            mu = h.mean(-1, keepdims=True)
            var = ((h - mu) ** 2).mean(-1, keepdims=True)
            return (h - mu) / jnp.sqrt(var + eps) * g + lb
        return nary(f, [ensure_tensor(x), ensure_tensor(residual),
                        self.linear_bias, self.ln_scale, self.ln_bias],
                    name="fused_bias_dropout_residual_layer_norm")

    def extra_repr(self):
        return (f"embed_dim={self.embed_dim}, seq_len=None, "
                f"dropout_rate={self.dropout_rate}, epsilon={self._epsilon}")
