"""Fused transformer blocks (ref: ``python/paddle/incubate/nn/``:
FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
``functional/fused_transformer.py``, memory_efficient_attention).

TPU-native: "fused" means "one XLA fusion region" — the whole block is
written as a single jnp composition (attention via
``F.scaled_dot_product_attention`` → Pallas flash kernel on TPU), so the
reference's hand-written fused CUDA kernels
(``paddle/phi/kernels/fusion/``) map to compiler fusions + Pallas.
"""
from .fused_transformer import (  # noqa: F401
    FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
    FusedMultiTransformer,
)
from .fused_extras import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd, FusedEcMoe,
    FusedLinear,
)
from . import functional  # noqa: F401
from .memory_efficient_attention import memory_efficient_attention  # noqa: F401
