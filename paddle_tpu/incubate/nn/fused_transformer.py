"""Fused transformer layers (ref: ``python/paddle/incubate/nn/layer/
fused_transformer.py``). One XLA fusion region per block; normalize_before
(pre-LN) matches the reference default for Fused* layers.
"""
from __future__ import annotations

import numpy as np

from ...nn.layer.layers import Layer
from ...nn import functional as F
from ...nn.layer.common import Linear, Dropout
from ...nn.layer.norm import LayerNorm
from ...tensor import Tensor

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer"]


class FusedMultiHeadAttention(Layer):
    """ref: fused_transformer.py FusedMultiHeadAttention — QKV in one
    matmul, flash attention, out proj, residual+LN fused by XLA."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.qkv = Linear(embed_dim, 3 * embed_dim,
                          weight_attr=qkv_weight_attr,
                          bias_attr=qkv_bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim,
                               weight_attr=linear_weight_attr,
                               bias_attr=linear_bias_attr)
        self.ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)

    def forward(self, x, attn_mask=None, cache=None):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        B, S = x.shape[0], x.shape[1]
        qkv = self.qkv(x)
        qkv = qkv.reshape([B, S, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate,
            training=self.training)
        out = out.reshape([B, S, self.embed_dim])
        out = self.dropout(self.out_proj(out))
        out = residual + out
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(Layer):
    """ref: fused_transformer.py FusedFeedForward."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self.linear1 = Linear(d_model, dim_feedforward,
                              weight_attr=linear1_weight_attr,
                              bias_attr=linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model,
                              weight_attr=linear2_weight_attr,
                              bias_attr=linear2_bias_attr)
        self.ln = LayerNorm(d_model, epsilon=epsilon)
        self.dropout1 = Dropout(act_dropout_rate if act_dropout_rate
                                is not None else dropout_rate)
        self.dropout2 = Dropout(dropout_rate)

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        act = getattr(F, self.activation)
        out = self.linear2(self.dropout1(act(self.linear1(x))))
        out = residual + self.dropout2(out)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    """ref: fused_transformer.py FusedTransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate
            is not None else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """ref: fused_transformer.py FusedMultiTransformer — N stacked decoder
    blocks driven from flat parameter lists (inference-style API)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 num_layers=1, nranks=1, ring_id=-1, name=None, **kw):
        super().__init__()
        from ...nn.layer.container import LayerList
        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=normalize_before)
            for _ in range(num_layers)])

    def forward(self, x, attn_mask=None, caches=None, **kw):
        for l in self.layers:
            x = l(x, src_mask=attn_mask)
        return x
