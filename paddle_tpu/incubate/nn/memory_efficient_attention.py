"""ref: ``python/paddle/incubate/nn/memory_efficient_attention.py`` (the
xformers-derived CUDA kernel). TPU-native: same API over the flash
attention path (Pallas on hardware, fused XLA otherwise)."""
from __future__ import annotations

from ...nn import functional as F

__all__ = ["memory_efficient_attention"]


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    return F.scaled_dot_product_attention(
        query, key, value, attn_mask=attn_bias, dropout_p=p,
        is_causal=False, training=training)
