"""ref: ``python/paddle/incubate/nn/functional/`` fused functional ops."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ....ops.op_utils import nary, ensure_tensor
from ....tensor import Tensor

__all__ = ["fused_rotary_position_embedding", "fused_rms_norm",
           "fused_dropout_add", "fused_linear", "swiglu",
           "fused_matmul_bias", "fused_ec_moe", "fused_gate_attention"]


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """RoPE (ref: ``fused_rope`` kernel ``paddle/phi/kernels/fusion/
    fused_rope_grad_kernel.h``). Layout (B, S, H, D)."""

    def rope_one(x, sin_, cos_):
        if use_neox_rotary_style:
            d = x.shape[-1]
            x1, x2 = x[..., : d // 2], x[..., d // 2:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
        else:
            x1 = x[..., ::2]
            x2 = x[..., 1::2]
            rot = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
        return x * cos_ + rot * sin_

    outs = []
    tensors = [t for t in (q, k, v) if t is not None]
    first = ensure_tensor(tensors[0])
    S, D = first.shape[1], first.shape[-1]
    if sin is None or cos is None:
        pos = jnp.arange(S)[:, None]
        inv = 1.0 / (10000.0 ** (jnp.arange(0, D, 2) / D))
        angles = pos * inv[None, :]
        if use_neox_rotary_style:
            emb = jnp.concatenate([angles, angles], axis=-1)
        else:
            emb = jnp.repeat(angles, 2, axis=-1)
        sin_a, cos_a = jnp.sin(emb), jnp.cos(emb)
    else:
        sin_a = ensure_tensor(sin)._data.reshape(S, D)
        cos_a = ensure_tensor(cos)._data.reshape(S, D)
    sin_b = sin_a[None, :, None, :]
    cos_b = cos_a[None, :, None, :]

    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        outs.append(nary(lambda x: rope_one(x, sin_b, cos_b),
                         [ensure_tensor(t)], name="fused_rope"))
    return tuple(outs)


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1):
    """RMSNorm in one fusion region."""
    args = [ensure_tensor(x)]
    if norm_weight is not None:
        args.append(ensure_tensor(norm_weight))

    def f(xd, *w):
        var = jnp.mean(xd.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        out = (xd.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon))
        out = out.astype(xd.dtype)
        if w:
            out = out * w[0]
        return out

    return nary(f, args, name="fused_rms_norm")


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train"):
    from ....nn import functional as F
    return F.dropout(x, p=p, training=training, mode=mode) + ensure_tensor(y)


def fused_linear(x, weight, bias=None, transpose_weight=False):
    from ....nn import functional as F
    w = ensure_tensor(weight)
    if transpose_weight:
        w = w.T
    return F.linear(x, w, bias)


def swiglu(x, y=None):
    """ref: fused swiglu kernel — silu(x) * y (y defaults to second half)."""
    x = ensure_tensor(x)
    if y is None:
        def f(xd):
            a, b = jnp.split(xd, 2, axis=-1)
            return jax.nn.silu(a) * b
        return nary(f, [x], name="swiglu")
    return nary(lambda a, b: jax.nn.silu(a) * b, [x, ensure_tensor(y)],
                name="swiglu")


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """ref: ``incubate/nn/functional/fused_matmul_bias.py`` — matmul with
    epilogue bias; XLA fuses the add into the MXU epilogue."""
    def f(xd, yd, *b):
        a = jnp.swapaxes(xd, -1, -2) if transpose_x else xd
        w = jnp.swapaxes(yd, -1, -2) if transpose_y else yd
        out = a @ w
        if b:
            out = out + b[0]
        return out

    args = [ensure_tensor(x), ensure_tensor(y)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return nary(f, args, name="fused_matmul_bias")


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu"):
    """ref: ``incubate/nn/functional/fused_ec_moe.py`` (CUDA 'moe' op,
    sm75+). Dense soft mixture: every token runs every expert's FFN and
    the outputs combine with softmax(gate) weights — one batched einsum
    pair over the expert dim, which GSPMD can shard on an expert axis.

    x [B,S,D]; gate [B,S,E]; bmm0 [E,D,F] (+bias [E,1,F]);
    bmm1 [E,F,D] (+bias [E,1,D]).
    """
    if act_type not in ("gelu", "relu"):
        raise ValueError(f"act_type must be gelu/relu, got {act_type!r}")

    def f(xd, gd, w0, b0, w1, b1):
        h = jnp.einsum("bsd,edf->bsef", xd, w0) + b0[None, :, 0]
        h = jax.nn.gelu(h, approximate=False) if act_type == "gelu" \
            else jax.nn.relu(h)
        y = jnp.einsum("bsef,efd->bsed", h, w1) + b1[None, :, 0]
        p = jax.nn.softmax(gd.astype(jnp.float32), axis=-1).astype(y.dtype)
        return jnp.einsum("bsed,bse->bsd", y, p)

    return nary(f, [ensure_tensor(a) for a in
                    (x, gate, bmm0_weight, bmm0_bias, bmm1_weight,
                     bmm1_bias)], name="fused_ec_moe")


def fused_gate_attention(query, key=None, query_weight=None, key_weight=None,
                         value_weight=None, qkv_weight=None,
                         gate_linear_weight=None, gate_linear_bias=None,
                         out_linear_weight=None, out_linear_bias=None,
                         nonbatched_bias=None, attn_mask=None,
                         has_gating=True, merge_qkv=True,
                         use_flash_attn=False):
    """ref: ``incubate/nn/functional/fused_gate_attention.py`` —
    AlphaFold-style gated attention over [B, msa, res, dim] inputs,
    following the reference pseudo-code exactly (einsum chain + sigmoid
    gate + output projection). One traced XLA program; the fused-kernel
    benefit comes from XLA fusion rather than a bespoke CUDA kernel."""
    tensors = {"q": ensure_tensor(query)}
    if merge_qkv:
        tensors["qkv_w"] = ensure_tensor(qkv_weight)
    else:
        tensors["k"] = ensure_tensor(key)
        tensors["qw"] = ensure_tensor(query_weight)
        tensors["kw"] = ensure_tensor(key_weight)
        tensors["vw"] = ensure_tensor(value_weight)
    if has_gating:
        tensors["gw"] = ensure_tensor(gate_linear_weight)
        tensors["gb"] = ensure_tensor(gate_linear_bias)
    tensors["ow"] = ensure_tensor(out_linear_weight)
    if out_linear_bias is not None:
        tensors["ob"] = ensure_tensor(out_linear_bias)
    if nonbatched_bias is not None:
        tensors["nb"] = ensure_tensor(nonbatched_bias)
    if attn_mask is not None:
        tensors["mask"] = ensure_tensor(attn_mask)
    keys = list(tensors)

    def f(*vals):
        t = dict(zip(keys, vals))
        qd = t["q"]
        if merge_qkv:
            # qkv_w [3, H, Dh, q_dim]
            q = jnp.einsum("nbqa,hca->nbqhc", qd, t["qkv_w"][0])
            k = jnp.einsum("nbka,hca->nbkhc", qd, t["qkv_w"][1])
            v = jnp.einsum("nbka,hca->nbkhc", qd, t["qkv_w"][2])
        else:
            q = jnp.einsum("nbqa,ahc->nbqhc", qd, t["qw"])
            k = jnp.einsum("nbka,ahc->nbkhc", t["k"], t["kw"])
            v = jnp.einsum("nbka,ahc->nbkhc", t["k"], t["vw"])
        c = q.shape[-1] ** (-0.5)
        logits = jnp.einsum("nbqhc,nbkhc->nbhqk", q * c, k)
        if "mask" in t:
            logits = logits + t["mask"].astype(logits.dtype)
        if "nb" in t:
            # ref: unsqueeze(nonbatched_bias, axis=1) — broadcast over msa
            logits = logits + jnp.expand_dims(t["nb"], 1).astype(
                logits.dtype)
        w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
            qd.dtype)
        avg = jnp.einsum("nbhqk,nbkhc->nbqhc", w, v)
        if has_gating:
            gate = jnp.einsum("nbqc,chv->nbqhv", qd, t["gw"]) + t["gb"]
            avg = avg * jax.nn.sigmoid(gate)
        out = jnp.einsum("nbqhc,hco->nbqo", avg, t["ow"])
        if "ob" in t:
            out = out + t["ob"]
        return out

    return nary(f, list(tensors.values()), name="fused_gate_attention")
