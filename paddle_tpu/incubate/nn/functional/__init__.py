"""ref: ``python/paddle/incubate/nn/functional/`` fused functional ops."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ....ops.op_utils import nary, ensure_tensor
from ....tensor import Tensor

__all__ = ["fused_rotary_position_embedding", "fused_rms_norm",
           "fused_dropout_add", "fused_linear", "swiglu"]


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """RoPE (ref: ``fused_rope`` kernel ``paddle/phi/kernels/fusion/
    fused_rope_grad_kernel.h``). Layout (B, S, H, D)."""

    def rope_one(x, sin_, cos_):
        if use_neox_rotary_style:
            d = x.shape[-1]
            x1, x2 = x[..., : d // 2], x[..., d // 2:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
        else:
            x1 = x[..., ::2]
            x2 = x[..., 1::2]
            rot = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
        return x * cos_ + rot * sin_

    outs = []
    tensors = [t for t in (q, k, v) if t is not None]
    first = ensure_tensor(tensors[0])
    S, D = first.shape[1], first.shape[-1]
    if sin is None or cos is None:
        pos = jnp.arange(S)[:, None]
        inv = 1.0 / (10000.0 ** (jnp.arange(0, D, 2) / D))
        angles = pos * inv[None, :]
        if use_neox_rotary_style:
            emb = jnp.concatenate([angles, angles], axis=-1)
        else:
            emb = jnp.repeat(angles, 2, axis=-1)
        sin_a, cos_a = jnp.sin(emb), jnp.cos(emb)
    else:
        sin_a = ensure_tensor(sin)._data.reshape(S, D)
        cos_a = ensure_tensor(cos)._data.reshape(S, D)
    sin_b = sin_a[None, :, None, :]
    cos_b = cos_a[None, :, None, :]

    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        outs.append(nary(lambda x: rope_one(x, sin_b, cos_b),
                         [ensure_tensor(t)], name="fused_rope"))
    return tuple(outs)


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1):
    """RMSNorm in one fusion region."""
    args = [ensure_tensor(x)]
    if norm_weight is not None:
        args.append(ensure_tensor(norm_weight))

    def f(xd, *w):
        var = jnp.mean(xd.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        out = (xd.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon))
        out = out.astype(xd.dtype)
        if w:
            out = out * w[0]
        return out

    return nary(f, args, name="fused_rms_norm")


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train"):
    from ....nn import functional as F
    return F.dropout(x, p=p, training=training, mode=mode) + ensure_tensor(y)


def fused_linear(x, weight, bias=None, transpose_weight=False):
    from ....nn import functional as F
    w = ensure_tensor(weight)
    if transpose_weight:
        w = w.T
    return F.linear(x, w, bias)


def swiglu(x, y=None):
    """ref: fused swiglu kernel — silu(x) * y (y defaults to second half)."""
    x = ensure_tensor(x)
    if y is None:
        def f(xd):
            a, b = jnp.split(xd, 2, axis=-1)
            return jax.nn.silu(a) * b
        return nary(f, [x], name="swiglu")
    return nary(lambda a, b: jax.nn.silu(a) * b, [x, ensure_tensor(y)],
                name="swiglu")
