"""ref: ``python/paddle/incubate/nn/functional/`` fused functional ops."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ....ops.op_utils import nary, ensure_tensor
from ....tensor import Tensor

__all__ = ["fused_rotary_position_embedding", "fused_rms_norm",
           "fused_dropout_add", "fused_linear", "swiglu",
           "fused_matmul_bias", "fused_ec_moe", "fused_gate_attention"]


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """RoPE (ref: ``fused_rope`` kernel ``paddle/phi/kernels/fusion/
    fused_rope_grad_kernel.h``). Layout (B, S, H, D)."""

    def rope_one(x, sin_, cos_):
        if use_neox_rotary_style:
            d = x.shape[-1]
            x1, x2 = x[..., : d // 2], x[..., d // 2:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
        else:
            x1 = x[..., ::2]
            x2 = x[..., 1::2]
            rot = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
        return x * cos_ + rot * sin_

    outs = []
    tensors = [t for t in (q, k, v) if t is not None]
    first = ensure_tensor(tensors[0])
    S, D = first.shape[1], first.shape[-1]
    if sin is None or cos is None:
        pos = jnp.arange(S)[:, None]
        inv = 1.0 / (10000.0 ** (jnp.arange(0, D, 2) / D))
        angles = pos * inv[None, :]
        if use_neox_rotary_style:
            emb = jnp.concatenate([angles, angles], axis=-1)
        else:
            emb = jnp.repeat(angles, 2, axis=-1)
        sin_a, cos_a = jnp.sin(emb), jnp.cos(emb)
    else:
        sin_a = ensure_tensor(sin)._data.reshape(S, D)
        cos_a = ensure_tensor(cos)._data.reshape(S, D)
    sin_b = sin_a[None, :, None, :]
    cos_b = cos_a[None, :, None, :]

    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        outs.append(nary(lambda x: rope_one(x, sin_b, cos_b),
                         [ensure_tensor(t)], name="fused_rope"))
    return tuple(outs)


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1):
    """RMSNorm in one fusion region."""
    args = [ensure_tensor(x)]
    if norm_weight is not None:
        args.append(ensure_tensor(norm_weight))

    def f(xd, *w):
        var = jnp.mean(xd.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        out = (xd.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon))
        out = out.astype(xd.dtype)
        if w:
            out = out * w[0]
        return out

    return nary(f, args, name="fused_rms_norm")


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train"):
    from ....nn import functional as F
    return F.dropout(x, p=p, training=training, mode=mode) + ensure_tensor(y)


def fused_linear(x, weight, bias=None, transpose_weight=False):
    from ....nn import functional as F
    w = ensure_tensor(weight)
    if transpose_weight:
        w = w.T
    return F.linear(x, w, bias)


def swiglu(x, y=None):
    """ref: fused swiglu kernel — silu(x) * y (y defaults to second half)."""
    x = ensure_tensor(x)
    if y is None:
        def f(xd):
            a, b = jnp.split(xd, 2, axis=-1)
            return jax.nn.silu(a) * b
        return nary(f, [x], name="swiglu")
    return nary(lambda a, b: jax.nn.silu(a) * b, [x, ensure_tensor(y)],
                name="swiglu")


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """ref: ``incubate/nn/functional/fused_matmul_bias.py`` — matmul with
    epilogue bias; XLA fuses the add into the MXU epilogue."""
    def f(xd, yd, *b):
        a = jnp.swapaxes(xd, -1, -2) if transpose_x else xd
        w = jnp.swapaxes(yd, -1, -2) if transpose_y else yd
        out = a @ w
        if b:
            out = out + b[0]
        return out

    args = [ensure_tensor(x), ensure_tensor(y)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return nary(f, args, name="fused_matmul_bias")


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu"):
    """ref: ``incubate/nn/functional/fused_ec_moe.py`` (CUDA 'moe' op,
    sm75+). Dense soft mixture: every token runs every expert's FFN and
    the outputs combine with softmax(gate) weights — one batched einsum
    pair over the expert dim, which GSPMD can shard on an expert axis.

    x [B,S,D]; gate [B,S,E]; bmm0 [E,D,F] (+bias [E,1,F]);
    bmm1 [E,F,D] (+bias [E,1,D]).
    """
    if act_type not in ("gelu", "relu"):
        raise ValueError(f"act_type must be gelu/relu, got {act_type!r}")

    def f(xd, gd, w0, b0, w1, b1):
        h = jnp.einsum("bsd,edf->bsef", xd, w0) + b0[None, :, 0]
        h = jax.nn.gelu(h, approximate=False) if act_type == "gelu" \
            else jax.nn.relu(h)
        y = jnp.einsum("bsef,efd->bsed", h, w1) + b1[None, :, 0]
        p = jax.nn.softmax(gd.astype(jnp.float32), axis=-1).astype(y.dtype)
        return jnp.einsum("bsed,bse->bsd", y, p)

    return nary(f, [ensure_tensor(a) for a in
                    (x, gate, bmm0_weight, bmm0_bias, bmm1_weight,
                     bmm1_bias)], name="fused_ec_moe")


def fused_gate_attention(query, key=None, query_weight=None, key_weight=None,
                         value_weight=None, qkv_weight=None,
                         gate_linear_weight=None, gate_linear_bias=None,
                         out_linear_weight=None, out_linear_bias=None,
                         nonbatched_bias=None, attn_mask=None,
                         has_gating=True, merge_qkv=True,
                         use_flash_attn=False):
    """ref: ``incubate/nn/functional/fused_gate_attention.py`` —
    AlphaFold-style gated attention over [B, msa, res, dim] inputs,
    following the reference pseudo-code exactly (einsum chain + sigmoid
    gate + output projection). One traced XLA program; the fused-kernel
    benefit comes from XLA fusion rather than a bespoke CUDA kernel."""
    tensors = {"q": ensure_tensor(query)}
    if merge_qkv:
        tensors["qkv_w"] = ensure_tensor(qkv_weight)
    else:
        tensors["k"] = ensure_tensor(key)
        tensors["qw"] = ensure_tensor(query_weight)
        tensors["kw"] = ensure_tensor(key_weight)
        tensors["vw"] = ensure_tensor(value_weight)
    if has_gating:
        tensors["gw"] = ensure_tensor(gate_linear_weight)
        tensors["gb"] = ensure_tensor(gate_linear_bias)
    tensors["ow"] = ensure_tensor(out_linear_weight)
    if out_linear_bias is not None:
        tensors["ob"] = ensure_tensor(out_linear_bias)
    if nonbatched_bias is not None:
        tensors["nb"] = ensure_tensor(nonbatched_bias)
    if attn_mask is not None:
        tensors["mask"] = ensure_tensor(attn_mask)
    keys = list(tensors)

    def f(*vals):
        t = dict(zip(keys, vals))
        qd = t["q"]
        if merge_qkv:
            # qkv_w [3, H, Dh, q_dim]
            q = jnp.einsum("nbqa,hca->nbqhc", qd, t["qkv_w"][0])
            k = jnp.einsum("nbka,hca->nbkhc", qd, t["qkv_w"][1])
            v = jnp.einsum("nbka,hca->nbkhc", qd, t["qkv_w"][2])
        else:
            q = jnp.einsum("nbqa,ahc->nbqhc", qd, t["qw"])
            k = jnp.einsum("nbka,ahc->nbkhc", t["k"], t["kw"])
            v = jnp.einsum("nbka,ahc->nbkhc", t["k"], t["vw"])
        c = q.shape[-1] ** (-0.5)
        logits = jnp.einsum("nbqhc,nbkhc->nbhqk", q * c, k)
        if "mask" in t:
            logits = logits + t["mask"].astype(logits.dtype)
        if "nb" in t:
            # ref: unsqueeze(nonbatched_bias, axis=1) — broadcast over msa
            logits = logits + jnp.expand_dims(t["nb"], 1).astype(
                logits.dtype)
        w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
            qd.dtype)
        avg = jnp.einsum("nbhqk,nbkhc->nbqhc", w, v)
        if has_gating:
            gate = jnp.einsum("nbqc,chv->nbqhv", qd, t["gw"]) + t["gb"]
            avg = avg * jax.nn.sigmoid(gate)
        out = jnp.einsum("nbqhc,hco->nbqo", avg, t["ow"])
        if "ob" in t:
            out = out + t["ob"]
        return out

    return nary(f, list(tensors.values()), name="fused_gate_attention")


def _fused_ln(h, g, b, eps):
    import jax.numpy as jnp
    mu = h.mean(-1, keepdims=True)
    var = ((h - mu) ** 2).mean(-1, keepdims=True)
    out = (h - mu) / jnp.sqrt(var + eps)
    if g is not None:
        out = out * g
    if b is not None:
        out = out + b
    return out


def _fused_drop(h, p, key, mode, training):
    """One dropout semantics for every fused block: train-time masking
    with upscale, or the downscale_in_infer (1-p) inference scaling."""
    import jax
    import jax.numpy as jnp
    if key is not None:
        keep = jax.random.bernoulli(key, 1.0 - p, h.shape)
        s = 1.0 / (1.0 - p) if mode == "upscale_in_train" else 1.0
        return jnp.where(keep, h * s, 0.0).astype(h.dtype)
    if mode == "downscale_in_infer" and not training and p > 0:
        return h * (1 - p)
    return h


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5,
                                           ln_epsilon=1e-5, training=True,
                                           mode="upscale_in_train",
                                           name=None):
    """layer_norm(residual + dropout(x + bias)) as one fusion region
    (ref ``incubate/nn/functional/fused_transformer.py:275``)."""
    import jax
    import jax.numpy as jnp
    from ....ops.op_utils import ensure_tensor, nary
    from ....framework import random as _random
    x, residual = ensure_tensor(x), ensure_tensor(residual)
    p = dropout_rate if training else 0.0
    key = _random.next_key() if p > 0 else None
    extras = [ensure_tensor(t) for t in (bias, ln_scale, ln_bias)
              if t is not None]
    has = [t is not None for t in (bias, ln_scale, ln_bias)]

    def f(xd, rd, *rest):
        it = iter(rest)
        b = next(it) if has[0] else None
        g = next(it) if has[1] else None
        lb = next(it) if has[2] else None
        h = xd + b if b is not None else xd
        h = _fused_drop(h, dropout_rate, key, mode, training)
        return _fused_ln(rd + h, g, lb, ln_epsilon)

    return nary(f, [x, residual] + extras,
                name="fused_bias_dropout_residual_layer_norm")


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """Transformer FFN block as one region (ref
    ``fused_transformer.py:32`` pseudo code, pre/post-LN variants)."""
    import jax
    import jax.numpy as jnp
    from ....ops.op_utils import ensure_tensor, nary
    from ....framework import random as _random
    x = ensure_tensor(x)
    p1 = dropout1_rate if training else 0.0
    p2 = dropout2_rate if training else 0.0
    k1 = _random.next_key() if p1 > 0 else None
    k2 = _random.next_key() if p2 > 0 else None
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[activation]
    opt = (linear1_bias, linear2_bias, ln1_scale, ln1_bias, ln2_scale,
           ln2_bias)
    has = [t is not None for t in opt]
    extras = [ensure_tensor(t) for t in opt if t is not None]

    def f(xd, w1, w2, *rest):
        it = iter(rest)
        vals = [next(it) if h else None for h in has]
        b1, b2, g1, lb1, g2, lb2 = vals
        residual = xd
        h = _fused_ln(xd, g1, lb1, ln1_epsilon) if pre_layer_norm else xd
        h = h @ w1
        if b1 is not None:
            h = h + b1
        h = _fused_drop(act(h), dropout1_rate, k1, mode, training)
        h = h @ w2
        if b2 is not None:
            h = h + b2
        h = _fused_drop(h, dropout2_rate, k2, mode, training)
        if add_residual:
            h = residual + h
        if not pre_layer_norm:
            h = _fused_ln(h, g2, lb2, ln2_epsilon)
        return h

    return nary(f, [x, ensure_tensor(linear1_weight),
                    ensure_tensor(linear2_weight)] + extras,
                name="fused_feedforward")


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """Self-attention block as one region (ref
    ``fused_transformer.py:465`` pseudo code): optional pre-LN, packed
    qkv projection (qkv_weight (3, H, h, D) or 2-D with
    ``transpose_qkv_wb``), scaled dot-product with mask + dropout,
    output projection, residual + post-LN."""
    import jax
    import jax.numpy as jnp
    from ....ops.op_utils import ensure_tensor, nary
    from ....framework import random as _random
    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention cache_kv: use "
            "nn.MultiHeadAttention's cache path for decoding")
    x = ensure_tensor(x)
    p_att = attn_dropout_rate if training else 0.0
    p_out = dropout_rate if training else 0.0
    ka = _random.next_key() if p_att > 0 else None
    ko = _random.next_key() if p_out > 0 else None
    opt = (pre_ln_scale, pre_ln_bias, ln_scale, ln_bias, qkv_bias,
           linear_bias, attn_mask)
    has = [t is not None for t in opt]
    extras = [ensure_tensor(t) for t in opt if t is not None]

    def f(xd, qkv_w, lin_w, *rest):
        it = iter(rest)
        vals = [next(it) if h else None for h in has]
        pg, pb, g, lb, qb, ob, mask = vals
        B, S, H = xd.shape
        residual = xd
        h = _fused_ln(xd, pg, pb, pre_ln_epsilon) if pre_layer_norm \
            else xd
        if transpose_qkv_wb:  # (H, 3H) layout
            if num_heads <= 0:
                raise ValueError(
                    "transpose_qkv_wb=True requires num_heads > 0")
            nh = num_heads
            qkv = h @ qkv_w
            if qb is not None:
                qkv = qkv + qb
            qkv = qkv.reshape(B, S, 3, nh, H // nh)
        else:  # (3, num_heads, head_dim, H) layout
            nh, hd = qkv_w.shape[1], qkv_w.shape[2]
            qkv = jnp.einsum("bsh,tndh->bstnd", h, qkv_w)
            if qb is not None:
                qkv = qkv + qb.reshape(3, nh, hd)[None, None]
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
        logits = jnp.einsum("bnqd,bnkd->bnqk", q, k) / jnp.sqrt(
            jnp.asarray(q.shape[-1], jnp.float32)).astype(xd.dtype)
        if mask is not None:
            logits = logits + mask.astype(logits.dtype)
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1) \
            .astype(xd.dtype)
        probs = _fused_drop(probs, attn_dropout_rate, ka, mode,
                            training)
        ctx = jnp.einsum("bnqk,bnkd->bnqd", probs, v)
        ctx = jnp.moveaxis(ctx, 1, 2).reshape(B, S, -1)
        out = ctx @ lin_w
        if ob is not None:
            out = out + ob
        out = _fused_drop(out, dropout_rate, ko, mode, training)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            out = _fused_ln(out, g, lb, ln_epsilon)
        return out

    return nary(f, [x, ensure_tensor(qkv_weight),
                    ensure_tensor(linear_weight)] + extras,
                name="fused_multi_head_attention")


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights,
                            qkv_biases, linear_weights, linear_biases,
                            ffn_ln_scales, ffn_ln_biases, ffn1_weights,
                            ffn1_biases, ffn2_weights, ffn2_biases,
                            pre_layer_norm=True, epsilon=1e-05,
                            cache_kvs=None, pre_caches=None,
                            seq_lens=None, rotary_embs=None,
                            rotary_emb_dims=0, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            activation="gelu", training=False,
                            mode="upscale_in_train", trans_qkvw=True,
                            ring_id=-1, name=None):
    """Stack of fused transformer layers (ref
    ``fused_transformer.py:873``): per-layer fused_multi_head_attention
    + fused_feedforward, weights given as per-layer lists."""
    if cache_kvs is not None or pre_caches is not None or \
            time_step is not None:
        raise NotImplementedError(
            "fused_multi_transformer decode caches: use the "
            "incubate.nn.FusedMultiTransformer layer for generation")
    out = x
    n_layers = len(qkv_weights)

    def at(lst, i):
        return None if lst is None else lst[i]

    if not trans_qkvw:
        raise NotImplementedError(
            "fused_multi_transformer with trans_qkvw=False: pass the "
            "(3, num_heads, head_dim, H) qkv weight layout instead")
    for i in range(n_layers):
        out = fused_multi_head_attention(
            out, qkv_weights[i], linear_weights[i],
            pre_layer_norm=pre_layer_norm, pre_ln_scale=at(ln_scales, i),
            pre_ln_bias=at(ln_biases, i), ln_scale=at(ln_scales, i),
            ln_bias=at(ln_biases, i), qkv_bias=at(qkv_biases, i),
            linear_bias=at(linear_biases, i), attn_mask=attn_mask,
            dropout_rate=dropout_rate, attn_dropout_rate=dropout_rate,
            pre_ln_epsilon=epsilon, ln_epsilon=epsilon,
            training=training, mode=mode, transpose_qkv_wb=False)
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=at(ffn1_biases, i), linear2_bias=at(ffn2_biases, i),
            ln1_scale=at(ffn_ln_scales, i), ln1_bias=at(ffn_ln_biases, i),
            ln2_scale=at(ffn_ln_scales, i), ln2_bias=at(ffn_ln_biases, i),
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, ln1_epsilon=epsilon,
            ln2_epsilon=epsilon, pre_layer_norm=pre_layer_norm,
            training=training, mode=mode)
    return out


__all__ += ["fused_bias_dropout_residual_layer_norm", "fused_feedforward",
            "fused_multi_head_attention", "fused_multi_transformer"]
