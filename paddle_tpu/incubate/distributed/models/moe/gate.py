"""Gate networks (ref: ``python/paddle/incubate/distributed/models/moe/
gate/{base_gate,naive_gate,gshard_gate,switch_gate}.py``).

A gate is a Layer producing routing logits (T, E); the routing math
itself (top-k, capacity, aux loss) lives in functional.py and is chosen
by ``top_k``.
"""
from __future__ import annotations

from .....nn import Layer, Linear

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]


class BaseGate(Layer):
    def __init__(self, num_expert, world_size=1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def forward(self, x):
        raise NotImplementedError

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss


class NaiveGate(BaseGate):
    """Plain linear gate, top-k chosen by the layer; no noise."""

    top_k = 2

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(num_expert, world_size)
        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp):
        return self.gate(inp)


class GShardGate(NaiveGate):
    """top-2 with capacity + load-balancing aux loss (gshard_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1,
                 capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=2)
        self.capacity_factor = capacity[0] if isinstance(
            capacity, (tuple, list)) else capacity


class SwitchGate(NaiveGate):
    """top-1 switch-transformer gate (switch_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1,
                 capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.capacity_factor = capacity[0] if isinstance(
            capacity, (tuple, list)) else capacity
