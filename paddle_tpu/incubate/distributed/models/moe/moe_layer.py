"""MoELayer — expert-parallel mixture of experts.

TPU-native redesign of ``python/paddle/incubate/distributed/models/moe/
moe_layer.py:263 MoELayer``: the reference's routing pipeline
(count_by_gate → limit_by_capacity CUDA ops → global_scatter /
global_gather NCCL all-to-alls) becomes GShard einsum dispatch/combine
(functional.py).  When the expert dimension is sharded over a mesh axis
(``moe_axis``), XLA lowers those einsums to all_to_all over ICI; on one
chip they're plain batched matmuls.  Either way the whole layer is one
differentiable XLA subgraph — no host-side routing.

Experts:
* ``ExpertMlp`` — stacked expert weights (E, D, Dff): the fast path,
  one einsum per projection for ALL experts (MXU-batched).
* any ``LayerList`` of per-expert Layers — generic fallback, looped.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from .....nn import Layer, LayerList, initializer
from .....tensor import Tensor
from .....ops.op_utils import nary
from ..... import ops
from .functional import combine, dispatch, top1_gating, top2_gating
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer", "ExpertMlp"]


class ExpertMlp(Layer):
    """E parallel FFN experts with stacked weights (E, D, Dff)."""

    def __init__(self, num_expert, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.num_expert = num_expert
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.activation = activation
        bound1 = 1.0 / math.sqrt(d_model)
        bound2 = 1.0 / math.sqrt(d_hidden)
        self.w1 = self.create_parameter(
            [num_expert, d_model, d_hidden],
            default_initializer=initializer.Uniform(-bound1, bound1))
        self.b1 = self.create_parameter(
            [num_expert, 1, d_hidden],
            default_initializer=initializer.Constant(0.0))
        self.w2 = self.create_parameter(
            [num_expert, d_hidden, d_model],
            default_initializer=initializer.Uniform(-bound2, bound2))
        self.b2 = self.create_parameter(
            [num_expert, 1, d_model],
            default_initializer=initializer.Constant(0.0))

    def forward(self, xe):
        """xe: Tensor (E, C, D) → (E, C, D)."""
        act = self.activation

        def f(x, w1, b1, w2, b2):
            h = jnp.einsum("ecd,edf->ecf", x, w1) + b1
            if act == "gelu":
                import jax
                h = jax.nn.gelu(h)
            else:
                h = jnp.maximum(h, 0)
            return jnp.einsum("ecf,efd->ecd", h, w2) + b2

        return nary(f, [xe, self.w1, self.b1, self.w2, self.b2],
                    name="expert_mlp")


class MoELayer(Layer):
    """ref: moe_layer.py:263. ``gate`` is a dict config ({"type":
    "gshard"|"switch"|"naive", "top_k": k}) or a BaseGate instance;
    ``experts`` an ExpertMlp or LayerList.

    The load-balancing aux loss of the last forward is in ``self.l_aux``
    (and on the gate via ``gate.get_loss()``) — add it to the training
    loss scaled by your aux weight.
    """

    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0,
                 capacity_factor=1.2, moe_axis=None, **kwargs):
        super().__init__()
        self.d_model = d_model
        if isinstance(experts, (list, tuple)):
            experts = LayerList(experts)
        self.experts = experts
        if isinstance(experts, ExpertMlp):
            self.num_expert = experts.num_expert
        else:
            self.num_expert = len(experts)
        if gate is None:
            gate = {"type": "gshard", "top_k": 2}
        if isinstance(gate, dict):
            typ = gate.get("type", "gshard")
            top_k = gate.get("top_k", 2)
            if typ == "switch" or top_k == 1:
                gate = SwitchGate(d_model, self.num_expert)
            elif typ == "naive":
                gate = NaiveGate(d_model, self.num_expert, topk=top_k)
            else:
                gate = GShardGate(d_model, self.num_expert)
        assert isinstance(gate, BaseGate)
        self.gate = gate
        self.top_k = getattr(gate, "top_k", 2)
        self.capacity_factor = capacity_factor
        self.moe_axis = moe_axis
        self.l_aux = None

    def _capacity(self, num_tokens):
        cap = int(math.ceil(
            self.top_k * self.capacity_factor * num_tokens
            / self.num_expert))
        return max(cap, 4)

    def forward(self, inp):
        x = inp if isinstance(inp, Tensor) else Tensor(inp)
        orig_shape = list(x.shape)
        d = orig_shape[-1]
        tokens = 1
        for s in orig_shape[:-1]:
            tokens *= s
        xt = ops.reshape(x, [tokens, d])

        logits = self.gate(xt)  # (T, E)
        cap = self._capacity(tokens)
        top_k = self.top_k

        def route(lg):
            if top_k == 1:
                comb, disp, aux, _, _ = top1_gating(lg, cap)
            else:
                comb, disp, aux = top2_gating(lg, cap)
            return comb, disp.astype(jnp.float32), aux

        comb, disp, aux = nary(route, [logits], name="moe_gating",
                               n_out=3)
        self.l_aux = aux
        self.gate.set_loss(aux)

        xe = nary(lambda xx, dd: dispatch(xx, dd), [xt, disp],
                  name="moe_dispatch")

        if isinstance(self.experts, ExpertMlp):
            ye = self.experts(xe)
        else:
            outs = []
            for i, expert in enumerate(self.experts):
                xi = ops.reshape(
                    ops.slice(xe, axes=[0], starts=[i], ends=[i + 1]),
                    [cap, d])
                outs.append(expert(xi))
            ye = ops.stack(outs, axis=0)

        y = nary(lambda cc, yy: combine(yy, cc), [comb, ye],
                 name="moe_combine")
        return ops.reshape(y, orig_shape)
