"""Mixture-of-experts / expert parallelism (ref:
``python/paddle/incubate/distributed/models/moe/``)."""
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .moe_layer import ExpertMlp, MoELayer  # noqa: F401
from . import functional  # noqa: F401
