"""Functional MoE core — GShard-style einsum dispatch/combine.

TPU-native redesign of the reference MoE
(``python/paddle/incubate/distributed/models/moe/moe_layer.py``): the
reference routes tokens with custom CUDA ops (``count_by_gate``,
``global_scatter``/``global_gather`` over NCCL).  On TPU the idiomatic
formulation is the GShard one: gating produces a dense one-hot
``dispatch`` mask (tokens × experts × capacity) and the routing IS two
einsums — XLA turns them into all_to_all when the expert axis is
sharded over the mesh, and they differentiate for free.

All functions here are pure jnp on raw arrays (tokens-major); the Layer
wrapper lives in moe_layer.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["top1_gating", "top2_gating", "dispatch", "combine"]


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def _positions_in_expert(mask):
    """Position of each token within its expert's buffer: cumsum over
    tokens of the expert one-hot, minus 1 (T, E)."""
    return jnp.cumsum(mask, axis=0) - mask


def top1_gating(logits, capacity, prior_count=None):
    """Switch-transformer routing (top-1).

    Args: logits (T, E); capacity per expert (int); ``prior_count``
    (T, E) — tokens already buffered per expert (used by top-2's second
    pass).
    Returns (combine (T,E,C), dispatch_bool (T,E,C), aux_loss, idx (T,)).
    Aux loss follows Switch: E * sum_e(f_e * p_e) where f_e is the
    fraction of tokens routed to e and p_e the mean gate prob.
    """
    t, e = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(gates, axis=-1)
    mask = _one_hot(idx, e)  # (T, E)

    density = jnp.mean(mask, axis=0)          # f_e
    density_proxy = jnp.mean(gates, axis=0)   # p_e
    aux = jnp.sum(density * density_proxy) * e

    pos = _positions_in_expert(mask)
    if prior_count is not None:
        pos = pos + prior_count
    in_cap = (jnp.sum(pos * mask, axis=-1) < capacity)
    mask = mask * in_cap[:, None]
    gate_val = jnp.sum(gates * mask, axis=-1)  # (T,)

    pos_idx = jnp.sum(pos * mask, axis=-1).astype(jnp.int32)
    disp = (mask[:, :, None] *
            _one_hot(pos_idx, capacity)[:, None, :])  # (T, E, C)
    comb = disp * gate_val[:, None, None]
    return comb, disp > 0, aux, gates, mask


def top2_gating(logits, capacity):
    """GShard top-2 routing: pick the best expert, mask it out, pick the
    second; normalize the two gate values; capacity respects first-pass
    buffering. Returns (combine, dispatch_bool, aux_loss)."""
    t, e = logits.shape
    comb1, disp1, aux, gates, mask1 = top1_gating(logits, capacity)

    # second choice from the renormalized remainder
    logits2 = jnp.where(mask1 > 0, -jnp.inf, logits.astype(jnp.float32))
    count1 = jnp.sum(mask1, axis=0, keepdims=True)  # tokens per expert
    comb2, disp2, _, _, _ = top1_gating(
        logits2, capacity,
        prior_count=jnp.broadcast_to(count1, (t, e)))

    denom = jnp.sum(comb1, axis=(1, 2)) + jnp.sum(comb2, axis=(1, 2))
    denom = jnp.where(denom > 0, denom, 1.0)
    comb = (comb1 + comb2) / denom[:, None, None]
    disp = jnp.logical_or(disp1, disp2)
    return comb, disp, aux


def dispatch(x, disp):
    """(T, D), (T, E, C) → expert inputs (E, C, D)."""
    return jnp.einsum("tec,td->ecd", disp.astype(x.dtype), x)


def combine(expert_out, comb):
    """(E, C, D), (T, E, C) → (T, D)."""
    return jnp.einsum("tec,ecd->td", comb.astype(expert_out.dtype),
                      expert_out)
