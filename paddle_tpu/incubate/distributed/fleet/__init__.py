"""ref ``python/paddle/incubate/distributed/fleet/__init__.py``."""
from ....distributed.fleet.recompute import (  # noqa: F401
    recompute_hybrid, recompute_sequential,
)

__all__ = ["recompute_sequential", "recompute_hybrid"]
