"""``paddle_tpu.incubate.distributed`` (ref:
``python/paddle/incubate/distributed/``)."""
from . import models  # noqa: F401

from . import fleet  # noqa: F401
