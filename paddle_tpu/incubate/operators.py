"""Legacy ``paddle.incubate`` operator aliases (ref:
``python/paddle/incubate/operators/``): the graph ops that later
graduated to ``paddle.geometric`` plus the fused-softmax helpers. The
implementations live in :mod:`paddle_tpu.geometric`; these wrappers
keep the incubate-era signatures (``pool_type`` instead of
``reduce_op``, buffer/flag arguments accepted and ignored — they tuned
the CUDA hashtable path)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.op_utils import ensure_tensor, nary

__all__ = [
    "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
    "graph_send_recv", "graph_khop_sampler", "graph_sample_neighbors",
    "graph_reindex", "identity_loss",
]


def softmax_mask_fuse(x, mask, name=None):
    """Fused softmax(x + mask) (ref
    ``operators/softmax_mask_fuse.py:20`` over the CUDA fused kernel;
    XLA fuses the add into the softmax on TPU)."""
    def f(xd, md):
        return jax.nn.softmax((xd.astype(jnp.float32)
                               + md.astype(jnp.float32)), axis=-1) \
            .astype(xd.dtype)
    return nary(f, [ensure_tensor(x), ensure_tensor(mask)],
                name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x):
    """Fused causal softmax: mask out the strictly-upper triangle (ref
    ``operators/softmax_mask_fuse_upper_triangle.py:20``)."""
    def f(xd):
        s, k = xd.shape[-2], xd.shape[-1]
        keep = jnp.tril(jnp.ones((s, k), bool))
        logits = jnp.where(keep, xd.astype(jnp.float32),
                           jnp.finfo(jnp.float32).min)
        return jax.nn.softmax(logits, axis=-1).astype(xd.dtype)
    return nary(f, [ensure_tensor(x)],
                name="softmax_mask_fuse_upper_triangle")


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Legacy spelling of :func:`paddle_tpu.geometric.send_u_recv`
    (ref ``operators/graph_send_recv.py:37``; ``pool_type`` became
    ``reduce_op`` on graduation)."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index,
                       reduce_op=str(pool_type).lower(),
                       out_size=out_size)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Legacy spelling of :func:`paddle_tpu.geometric.sample_neighbors`
    (ref ``operators/graph_sample_neighbors.py:28``); the perm-buffer
    args tuned the CUDA fisher-yates path and are accepted unused."""
    from ..geometric import sample_neighbors
    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, eids=eids,
                            return_eids=return_eids)


def graph_reindex(x, neighbors, count, value_buffer=None,
                  index_buffer=None, flag_buffer_hashtable=False,
                  name=None):
    """Legacy spelling of :func:`paddle_tpu.geometric.reindex_graph`
    (ref ``operators/graph_reindex.py:28``)."""
    from ..geometric import reindex_graph
    return reindex_graph(x, neighbors, count)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling + subgraph reindex (ref
    ``operators/graph_khop_sampler.py:21``): one
    :func:`~paddle_tpu.geometric.sample_neighbors` round per entry of
    ``sample_sizes`` over the frontier, then one reindex of the union.
    Returns (edge_src, edge_dst, sample_index, reindex_nodes
    [, edge_eids])."""
    from ..geometric import sample_neighbors, reindex_graph
    from ..tensor import Tensor

    frontier = ensure_tensor(input_nodes)
    seeds_np = np.asarray(frontier._data).ravel()
    sample_sizes = list(sample_sizes)  # may be a one-shot iterator
    if not sample_sizes:  # degenerate: seeds only, no edges
        empty = Tensor(jnp.asarray(np.zeros((0,), seeds_np.dtype)))
        out_nodes = Tensor(jnp.asarray(seeds_np))
        reindex_nodes = Tensor(jnp.asarray(
            np.arange(len(seeds_np), dtype=seeds_np.dtype)))
        out = (empty, empty, out_nodes, reindex_nodes)
        return out + (empty,) if return_eids else out
    all_neighbors, all_counts, all_eids = [], [], []
    centers = []
    for hop, size in enumerate(sample_sizes):
        res = sample_neighbors(row, colptr, frontier,
                               sample_size=int(size),
                               eids=sorted_eids,
                               return_eids=return_eids)
        if return_eids:
            nbr, cnt, eid = res
            all_eids.append(np.asarray(eid._data).ravel())
        else:
            nbr, cnt = res
        nbr_np = np.asarray(nbr._data).ravel()
        cnt_np = np.asarray(cnt._data).ravel()
        centers.append(np.asarray(frontier._data).ravel())
        all_neighbors.append(nbr_np)
        all_counts.append(cnt_np)
        # next frontier: the new neighbors (dedup, keep order)
        frontier = Tensor(jnp.asarray(
            np.unique(nbr_np) if len(nbr_np) else nbr_np))
    # union subgraph: per-hop center/neighbor lists concatenate; the
    # reindex covers seeds + every sampled node
    x_nodes = np.concatenate(centers)
    neighbors = np.concatenate(all_neighbors) if all_neighbors else \
        np.zeros((0,), seeds_np.dtype)
    counts = np.concatenate(all_counts) if all_counts else \
        np.zeros((0,), np.int32)
    eids_flat = (np.concatenate(all_eids) if all_eids
                 else np.zeros((0,), seeds_np.dtype)) if return_eids \
        else None
    # reindex_graph wants unique center ids; dedup while preserving
    # first occurrence, remapping counts accordingly. eids travel with
    # their neighbor segments through the SAME regrouping so the i-th
    # eid still labels the i-th output edge.
    uniq, first_idx = np.unique(x_nodes, return_index=True)
    order = np.argsort(first_idx)
    uniq_ordered = uniq[order]
    seg_starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
    per_center: dict = {int(c): [] for c in uniq_ordered}
    for c, s, n in zip(x_nodes, seg_starts, counts):
        sl = slice(int(s), int(s) + int(n))
        per_center[int(c)].append(
            (neighbors[sl], eids_flat[sl] if return_eids else None))
    merged_counts = np.asarray(
        [sum(len(a) for a, _ in per_center[int(c)])
         for c in uniq_ordered],
        dtype=counts.dtype if counts.size else np.int32)
    merged_neighbors = np.concatenate(
        [a for c in uniq_ordered for a, _ in per_center[int(c)]]) \
        if neighbors.size else neighbors
    reindex_src, reindex_dst, out_nodes = reindex_graph(
        Tensor(jnp.asarray(uniq_ordered)),
        Tensor(jnp.asarray(merged_neighbors)),
        Tensor(jnp.asarray(merged_counts)))
    out_nodes_np = np.asarray(out_nodes._data).ravel()
    pos = {int(n): i for i, n in enumerate(out_nodes_np)}
    reindex_nodes = Tensor(jnp.asarray(
        np.asarray([pos[int(n)] for n in seeds_np],
                   dtype=seeds_np.dtype)))
    out = (reindex_src, reindex_dst, out_nodes, reindex_nodes)
    if return_eids:
        merged_eids = np.concatenate(
            [e for c in uniq_ordered for _, e in per_center[int(c)]]) \
            if neighbors.size else eids_flat
        return out + (Tensor(jnp.asarray(merged_eids)),)
    return out


def identity_loss(x, reduction="none"):
    """Loss-marker op (ref ``incubate/nn/loss.py:21``; IPU used it to
    anchor backprop — here it is the documented reduction)."""
    if isinstance(reduction, str):
        reduction = {"sum": 0, "mean": 1, "none": 2}.get(reduction.lower())
        if reduction is None:
            raise Exception("Unsupported reduction type.")
    t = ensure_tensor(x)
    if reduction == 0:
        return t.sum()
    if reduction == 1:
        return t.mean()
    if reduction == 2:
        return t
    raise Exception("Unsupported reduction type.")
