"""``paddle_tpu.incubate`` (ref: ``python/paddle/incubate/``): fused nn
blocks, model zoo (GPT flagship), distributed extras."""
from . import nn  # noqa: F401
from . import models  # noqa: F401
from . import autograd  # noqa: F401
from . import autotune  # noqa: F401
from . import asp  # noqa: F401
from . import multiprocessing  # noqa: F401
from .optimizer import (  # noqa: F401
    LookAhead, ModelAverage, DistributedFusedLamb)
