"""``paddle_tpu.incubate`` (ref: ``python/paddle/incubate/``): fused nn
blocks, model zoo (GPT flagship), distributed extras."""
from . import nn  # noqa: F401
from . import models  # noqa: F401
from . import autograd  # noqa: F401
from . import autotune  # noqa: F401
from . import asp  # noqa: F401
from . import multiprocessing  # noqa: F401
from . import distributed  # noqa: F401
from .operators import (  # noqa: F401
    graph_khop_sampler, graph_reindex, graph_sample_neighbors,
    graph_send_recv, identity_loss, softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)
# graduated-to-geometric math kept at the incubate spelling too
from ..geometric import (  # noqa: F401
    segment_max, segment_mean, segment_min, segment_sum,
)
from .optimizer import (  # noqa: F401
    LookAhead, ModelAverage, DistributedFusedLamb)
