"""ERNIE encoder family (baseline config[4]: ERNIE-3.0 pretraining with
AMP O2 + recompute).

The reference trains ERNIE through the same in-repo machinery this
framework re-designs (AMP ``python/paddle/amp/auto_cast.py:646``,
recompute ``fleet/recompute/recompute.py``, the BERT-style encoder
blocks of ``test/dygraph_to_static/bert_dygraph_model.py``; the model
definition itself lives in PaddleNLP's ``ErnieModel``). Architecturally
ERNIE is a post-LN transformer encoder with an extra TASK-TYPE embedding
(ERNIE 2.0/3.0 continual multi-task pretraining) and sentence-order /
masked-LM heads.

TPU-first: reuses the BERT blocks (bf16 AMP, flash attention), adds
per-block ``jax.checkpoint`` recompute via ``use_recompute`` — the
config[4] recipe compiles to ONE XLA train step like GPT/BERT.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ...tensor import Tensor
from ...nn.layer.layers import Layer
from ...nn.layer.common import Linear, Dropout, Embedding
from ...nn.layer.norm import LayerNorm
from ...nn.layer.container import LayerList
from ...nn import functional as F
from .bert import BertLayer, additive_attention_mask, run_encoder

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForSequenceClassification",
           "ErnieForPretraining", "ErniePretrainingCriterion",
           "ernie_tiny", "ernie_1_0", "ernie_3_0_base"]


@dataclasses.dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    task_type_vocab_size: int = 16   # ERNIE 2.0+ continual-task embedding
    use_task_id: bool = True
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    use_recompute: bool = False


class ErnieEmbeddings(Layer):
    """word + position + token-type (+ task-type) embeddings → LN →
    dropout (ref ErnieModel embeddings)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size)
        self.use_task_id = cfg.use_task_id
        if cfg.use_task_id:
            self.task_type_embeddings = Embedding(cfg.task_type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        seq_len = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(seq_len)[None, :])
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros((1, seq_len), jnp.int32))
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        if self.use_task_id:
            if task_type_ids is None:
                task_type_ids = Tensor(jnp.zeros((1, seq_len), jnp.int32))
            emb = emb + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(emb))


class ErnieModel(Layer):
    """Encoder + pooler; blocks are the shared BERT-style post-LN
    transformer layers (duck-typed config)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.config = cfg  # Engine strategy.recompute hook
        self.embeddings = ErnieEmbeddings(cfg)
        self.encoder = LayerList([BertLayer(cfg)
                                  for _ in range(cfg.num_layers)])
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        attention_mask = additive_attention_mask(attention_mask)
        x = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        x = run_encoder(self.encoder, x, attention_mask,
                        self.cfg.use_recompute, self.training)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForSequenceClassification(Layer):
    def __init__(self, cfg: ErnieConfig, num_classes=2):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.config = cfg
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.classifier = Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids,
                               attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))


class ErnieForPretraining(Layer):
    """MLM + sentence-order-prediction heads (ERNIE pretraining)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.config = cfg
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_ln = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.mlm_bias = self.create_parameter([cfg.vocab_size],
                                              is_bias=True)
        self.sop = Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None):
        seq, pooled = self.ernie(input_ids, token_type_ids,
                                 attention_mask=attention_mask,
                                 task_type_ids=task_type_ids)
        h = self.mlm_ln(F.gelu(self.mlm_transform(seq)))
        # tied decoder: logits = h @ word_emb^T + bias
        w = self.ernie.embeddings.word_embeddings.weight
        mlm_logits = F.linear(h, w.transpose([1, 0])) + self.mlm_bias
        sop_logits = self.sop(pooled)
        return mlm_logits, sop_logits


class ErniePretrainingCriterion(Layer):
    def forward(self, mlm_logits, sop_logits, masked_lm_labels,
                sentence_order_labels, masked_lm_weights=None):
        mlm = F.cross_entropy(
            mlm_logits.reshape([-1, mlm_logits.shape[-1]]),
            masked_lm_labels.reshape([-1]), reduction="none")
        if masked_lm_weights is not None:
            w = masked_lm_weights.reshape([-1]).astype("float32")
            mlm = (mlm * w).sum() / (w.sum() + 1e-6)
        else:
            mlm = mlm.mean()
        sop = F.cross_entropy(sop_logits, sentence_order_labels)
        return mlm + sop


def ernie_tiny(**kw):
    return ErnieConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                       num_attention_heads=2, intermediate_size=128,
                       max_position_embeddings=128,
                       task_type_vocab_size=4, **kw)


def ernie_1_0(**kw):
    kw.setdefault("use_task_id", False)
    return ErnieConfig(vocab_size=18000, **kw)


def ernie_3_0_base(**kw):
    """Config[4] class: ERNIE 3.0 base (12L/768H, task embeddings)."""
    return ErnieConfig(vocab_size=40000, **kw)
