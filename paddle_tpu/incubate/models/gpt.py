"""GPT model family — the flagship hybrid-parallel LLM.

The reference ships GPT in PaddleNLP built from the in-repo pieces this
framework re-designs: VocabParallelEmbedding / Column-Row parallel linears
(``fleet/layers/mpu/mp_layers.py``), fused attention+FFN
(``paddle/phi/kernels/fusion/``), flash attention
(``paddle/phi/kernels/gpu/flash_attn_kernel.cu``), recompute
(``fleet/recompute/``), hybrid dp×mp×pp scheduling (SURVEY §3.3, baseline
config[3]: GPT-3 1.3B).

TPU-first design decisions:
 - ONE logical model: parameters carry ``PartitionSpec`` annotations
   (embedding/vocab over ``mp``, QKV/out/MLP per Megatron, everything
   optionally fsdp-sharded over ``sharding``); GSPMD partitions the jitted
   train step — no per-rank model surgery.
 - attention is ``F.scaled_dot_product_attention`` (Pallas flash kernel on
   TPU hardware), bf16-first.
 - sequence axis can be sharded (``sep``) for long context — constraint
   hints are placed on the activations; ring attention rides
   ``paddle_tpu.nn.functional.ring_attention`` when enabled.
 - recompute per decoder block via ``jax.checkpoint``.
"""
from __future__ import annotations

import dataclasses
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...tensor import Tensor
from ...nn.layer.layers import Layer
from ...nn.layer.common import Linear, Dropout, Embedding
from ...nn.layer.norm import LayerNorm
from ...nn.layer.container import LayerList
from ...nn import functional as F
from ...nn import initializer as I
from ...distributed.fleet.meta_parallel import (
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy)
from ...distributed import mesh as _mesh_mod
from ..nn.functional import fused_rotary_position_embedding

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPretrainingCriterion", "gpt_tiny", "gpt_345m", "gpt_1p3b",
           "gpt_6p7b", "gpt_13b"]


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304          # padded to a multiple of 128 for MXU
    hidden_size: int = 2048
    num_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int = 0       # 0 → 4*hidden
    max_position_embeddings: int = 2048
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    use_rope: bool = False           # GPT-3 uses learned positions
    tie_word_embeddings: bool = True
    use_recompute: bool = False
    recompute_policy: str | None = None  # see fleet.recompute._POLICIES
    tensor_parallel: bool = True     # annotate megatron specs

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size


def _seq_constraint(t: Tensor) -> Tensor:
    """Shard the sequence axis over 'sep' when that axis exists (>1)."""
    if _mesh_mod.mesh_axis_size("sep") <= 1:
        return t
    mesh = _mesh_mod.get_mesh(create_default=False)
    if mesh is None or not isinstance(t._data, jax.core.Tracer):
        return t
    from ...distributed._jax_compat import in_compat_manual_region
    if in_compat_manual_region():
        return t
    from jax.sharding import NamedSharding
    from ...distributed.auto_parallel.spec_layout import default_layout
    try:
        t._data = jax.lax.with_sharding_constraint(
            t._data, NamedSharding(mesh, default_layout().batch_seq()))
    except Exception:
        pass
    return t


class GPTAttention(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h, heads = cfg.hidden_size, cfg.num_attention_heads
        self.num_heads = heads
        self.head_dim = h // heads
        self.use_rope = cfg.use_rope
        init = I.Normal(std=cfg.initializer_range)
        if cfg.tensor_parallel:
            self.qkv_proj = ColumnParallelLinear(
                h, 3 * h, gather_output=False, weight_attr=init)
            self.out_proj = RowParallelLinear(
                h, h, input_is_parallel=True, weight_attr=init)
        else:
            self.qkv_proj = Linear(h, 3 * h, weight_attr=init)
            self.out_proj = Linear(h, h, weight_attr=init)
        self.attn_dropout_p = cfg.attention_probs_dropout_prob

    def forward(self, x, attn_mask=None):
        B, S = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        qkv = qkv.reshape([B, S, self.num_heads, 3 * self.head_dim])
        q = qkv[..., : self.head_dim]
        k = qkv[..., self.head_dim: 2 * self.head_dim]
        v = qkv[..., 2 * self.head_dim:]
        if self.use_rope:
            q, k, _ = fused_rotary_position_embedding(q, k)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None,
            dropout_p=self.attn_dropout_p, training=self.training)
        out = out.reshape([B, S, self.num_heads * self.head_dim])
        return self.out_proj(out)


class GPTMLP(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        init = I.Normal(std=cfg.initializer_range)
        out_init = I.Normal(
            std=cfg.initializer_range / math.sqrt(2 * cfg.num_layers))
        if cfg.tensor_parallel:
            self.fc1 = ColumnParallelLinear(
                cfg.hidden_size, cfg.intermediate_size, gather_output=False,
                weight_attr=init)
            self.fc2 = RowParallelLinear(
                cfg.intermediate_size, cfg.hidden_size,
                input_is_parallel=True, weight_attr=out_init)
        else:
            self.fc1 = Linear(cfg.hidden_size, cfg.intermediate_size,
                              weight_attr=init)
            self.fc2 = Linear(cfg.intermediate_size, cfg.hidden_size,
                              weight_attr=out_init)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTDecoderLayer(Layer):
    """Pre-LN decoder block."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.attn = GPTAttention(cfg)
        self.ln2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.mlp = GPTMLP(cfg)
        self.dropout1 = Dropout(cfg.hidden_dropout_prob)
        self.dropout2 = Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = _seq_constraint(x)
        x = x + self.dropout1(self.attn(self.ln1(x), attn_mask))
        x = x + self.dropout2(self.mlp(self.ln2(x)))
        return x


class GPTEmbeddings(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        init = I.Normal(std=cfg.initializer_range)
        if cfg.tensor_parallel:
            self.word_embeddings = VocabParallelEmbedding(
                cfg.vocab_size, cfg.hidden_size, weight_attr=init)
        else:
            self.word_embeddings = Embedding(
                cfg.vocab_size, cfg.hidden_size, weight_attr=init)
        self.use_rope = cfg.use_rope
        if not cfg.use_rope:
            self.position_embeddings = Embedding(
                cfg.max_position_embeddings, cfg.hidden_size,
                weight_attr=init)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, position_ids=None):
        x = self.word_embeddings(input_ids)
        if not self.use_rope:
            if position_ids is None:
                S = input_ids.shape[1]
                position_ids = Tensor(jnp.arange(S, dtype=jnp.int32)[None, :])
            x = x + self.position_embeddings(position_ids)
        return self.dropout(x)


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = GPTEmbeddings(cfg)
        self.layers = LayerList([GPTDecoderLayer(cfg)
                                 for _ in range(cfg.num_layers)])
        self.final_ln = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_epsilon)
        self.use_recompute = cfg.use_recompute
        self.recompute_policy = cfg.recompute_policy

    def forward(self, input_ids, position_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, position_ids)
        from ...distributed.fleet.meta_parallel.pp_spmd import \
            current_pipeline_executor
        pexec = current_pipeline_executor()
        if pexec is not None:
            # compiled SPMD pipeline over the decoder stack (pp mesh axis)
            x = pexec(x, attention_mask)
        elif self.use_recompute:
            from ...distributed.fleet.recompute import recompute
            for layer in self.layers:
                x = recompute(layer, x, attention_mask,
                              policy=self.recompute_policy)
        else:
            for layer in self.layers:
                x = layer(x, attention_mask)
        return self.final_ln(x)


class GPTForCausalLM(Layer):
    """GPT + LM head (tied to the word embedding by default, like the
    reference's GPTForPretraining)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.config = cfg
        self.gpt = GPTModel(cfg)
        self.tie = cfg.tie_word_embeddings
        if not self.tie:
            init = I.Normal(std=cfg.initializer_range)
            if cfg.tensor_parallel:
                self.lm_head = ColumnParallelLinear(
                    cfg.hidden_size, cfg.vocab_size, has_bias=False,
                    gather_output=False, weight_attr=init)
            else:
                self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size,
                                      weight_attr=init, bias_attr=False)

    def pipeline_blocks(self):
        """Pipeline-parallel adapter (consumed by
        ``distributed.train_step.build_train_step`` when the mesh has a
        ``pp`` axis): the homogeneous decoder stack to shard over stages.

        Returns (block_param_prefixes, block_layer): prefixes name each
        block's parameters in ``named_parameters()`` order; ``block_layer``
        is one representative block for functional per-stage calls.
        """
        n = len(self.gpt.layers)
        return ([f"gpt.layers.{i}." for i in range(n)], self.gpt.layers[0])

    def forward(self, input_ids, position_ids=None, attention_mask=None):
        x = self.gpt(input_ids, position_ids, attention_mask)
        if self.tie:
            from ...ops.op_utils import nary
            w = self.gpt.embeddings.word_embeddings.weight
            logits = nary(lambda h, wt: jnp.einsum("bsh,vh->bsv", h, wt),
                          [x, w], name="lm_head_tied")
        else:
            logits = self.lm_head(x)
        return logits


class GPTPretrainingCriterion(Layer):
    """Causal-LM loss over (possibly vocab-sharded) logits."""

    def __init__(self, cfg: GPTConfig | None = None):
        super().__init__()
        self.ce = ParallelCrossEntropy()

    def forward(self, logits, labels, loss_mask=None):
        loss = self.ce(logits, labels)  # [B, S, 1]
        from ... import ops
        loss2d = loss.reshape([-1])
        if loss_mask is not None:
            m = loss_mask.reshape([-1]).astype("float32")
            return (loss2d * m).sum() / ops.math.clip(m.sum(), 1e-6, None)
        return loss2d.mean()


# -- canonical configs ------------------------------------------------------

def gpt_tiny(**kw):
    return GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                     num_attention_heads=4, max_position_embeddings=128,
                     **kw)


def gpt_345m(**kw):
    return GPTConfig(hidden_size=1024, num_layers=24,
                     num_attention_heads=16, **kw)


def gpt_1p3b(**kw):
    """Baseline config[3]: GPT-3 1.3B (hidden 2048, 24 layers, 16 heads)."""
    return GPTConfig(hidden_size=2048, num_layers=24,
                     num_attention_heads=16, **kw)


def gpt_6p7b(**kw):
    return GPTConfig(hidden_size=4096, num_layers=32,
                     num_attention_heads=32, **kw)


def gpt_13b(**kw):
    return GPTConfig(hidden_size=5120, num_layers=40,
                     num_attention_heads=40, **kw)
