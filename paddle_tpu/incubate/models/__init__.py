from .gpt import (  # noqa: F401
    GPTConfig, GPTModel, GPTForCausalLM, GPTPretrainingCriterion,
    gpt_tiny, gpt_345m, gpt_1p3b, gpt_6p7b, gpt_13b,
)
