from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForSequenceClassification,
    BertForPretraining, BertPretrainingCriterion, bert_tiny, bert_base,
    bert_large,
)
from .gpt import (  # noqa: F401
    GPTConfig, GPTModel, GPTForCausalLM, GPTPretrainingCriterion,
    gpt_tiny, gpt_345m, gpt_1p3b, gpt_6p7b, gpt_13b,
)
from .ernie import (  # noqa: F401
    ErnieConfig, ErnieModel, ErnieForSequenceClassification,
    ErnieForPretraining, ErniePretrainingCriterion, ernie_tiny, ernie_1_0,
    ernie_3_0_base)
