from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForSequenceClassification,
    BertForPretraining, BertPretrainingCriterion, bert_tiny, bert_base,
    bert_large,
)
from .gpt import (  # noqa: F401
    GPTConfig, GPTModel, GPTForCausalLM, GPTPretrainingCriterion,
    gpt_tiny, gpt_345m, gpt_1p3b, gpt_6p7b, gpt_13b,
)
