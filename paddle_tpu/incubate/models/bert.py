"""BERT encoder family (baseline config[1]: BERT-base SST-2 finetune under
to_static).

The reference exercises BERT through its dygraph→static tests
(``test/dygraph_to_static/bert_dygraph_model.py``: PrePostProcessLayer /
MultiHeadAttention / encoder stack + pretraining heads) with the same
building blocks this framework re-designs. TPU-first choices mirror GPT:
bf16-first compute via AMP, ``F.scaled_dot_product_attention`` (Pallas
flash path on hardware), optional TP via the same parallel layers, and the
whole finetune step compiled by ``to_static`` into one XLA program.
"""
from __future__ import annotations

import dataclasses
import math

import numpy as np
import jax.numpy as jnp

from ...tensor import Tensor
from ...nn.layer.layers import Layer
from ...nn.layer.common import Linear, Dropout, Embedding
from ...nn.layer.norm import LayerNorm
from ...nn.layer.container import LayerList
from ...nn import functional as F

__all__ = ["BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForPretraining", "BertPretrainingCriterion", "bert_tiny",
           "bert_base", "bert_large"]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30528          # padded to a multiple of 64 for MXU
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12


class BertEmbeddings(Layer):
    """word + position + token-type embeddings → LN → dropout."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size)
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        seq_len = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(seq_len)[None, :])
        if token_type_ids is None:
            token_type_ids = Tensor(
                jnp.zeros((1, seq_len), jnp.int32))
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertSelfAttention(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.num_heads = cfg.num_attention_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.qkv = Linear(cfg.hidden_size, 3 * cfg.hidden_size)
        self.out = Linear(cfg.hidden_size, cfg.hidden_size)
        self.attn_drop = cfg.attention_probs_dropout_prob
        self.proj_drop = Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, attention_mask=None):
        B, T, H = x.shape
        qkv = self.qkv(x).reshape([B, T, 3, self.num_heads, self.head_dim])
        q, k, v = (qkv[:, :, i] for i in range(3))
        ctx = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attention_mask,
            dropout_p=self.attn_drop if self.training else 0.0,
            is_causal=False)
        return self.proj_drop(self.out(ctx.reshape([B, T, H])))


class BertLayer(Layer):
    """post-LN transformer block (BERT convention)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(cfg)
        self.ln1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.fc1 = Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc2 = Linear(cfg.intermediate_size, cfg.hidden_size)
        self.ln2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, attention_mask=None):
        x = self.ln1(x, residual=self.attention(x, attention_mask))
        h = self.fc2(F.gelu(self.fc1(x)))
        return self.ln2(x, residual=self.dropout(h))


def additive_attention_mask(attention_mask):
    """[B, T] padding mask → additive [B, 1, 1, T] (shared by the BERT
    and ERNIE encoders)."""
    if attention_mask is not None and len(attention_mask.shape) == 2:
        m = attention_mask.astype("float32")
        return (m - 1.0).unsqueeze(1).unsqueeze(1) * 1e4
    return attention_mask


def run_encoder(layers, x, attention_mask, use_recompute, training):
    """Encoder stack loop, optionally rematerialized per block
    (``jax.checkpoint`` via fleet.recompute — the config[4] recipe)."""
    if use_recompute and training:
        from ...distributed.fleet.recompute import recompute
        for layer in layers:
            x = recompute(layer, x, attention_mask)
    else:
        for layer in layers:
            x = layer(x, attention_mask)
    return x


class BertModel(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.encoder = LayerList([BertLayer(cfg)
                                  for _ in range(cfg.num_layers)])
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        attention_mask = additive_attention_mask(attention_mask)
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        x = run_encoder(self.encoder, x, attention_mask,
                        getattr(self.cfg, "use_recompute", False),
                        self.training)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForSequenceClassification(Layer):
    """SST-2-style finetune head (config[1])."""

    def __init__(self, cfg: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.classifier = Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))


class BertForPretraining(Layer):
    """MLM + NSP heads (ref bert_dygraph_model.py PretrainModelLayer)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_ln = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.mlm_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)
        self.nsp = Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_positions=None):
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask=attention_mask)
        h = self.mlm_ln(F.gelu(self.mlm_transform(seq)))
        # tied decoder: logits = h @ word_emb^T + bias
        w = self.bert.embeddings.word_embeddings.weight
        mlm_logits = F.linear(h, w.transpose([1, 0])) + self.mlm_bias
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


class BertPretrainingCriterion(Layer):
    def forward(self, mlm_logits, nsp_logits, masked_lm_labels,
                next_sentence_labels, masked_lm_weights=None):
        mlm = F.cross_entropy(
            mlm_logits.reshape([-1, mlm_logits.shape[-1]]),
            masked_lm_labels.reshape([-1]), reduction="none")
        if masked_lm_weights is not None:
            w = masked_lm_weights.reshape([-1]).astype("float32")
            mlm = (mlm * w).sum() / (w.sum() + 1e-6)
        else:
            mlm = mlm.mean()
        nsp = F.cross_entropy(nsp_logits, next_sentence_labels)
        return mlm + nsp


def bert_tiny(**kw):
    return BertConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                      num_attention_heads=2, intermediate_size=128,
                      max_position_embeddings=128, **kw)


def bert_base(**kw):
    return BertConfig(**kw)


def bert_large(**kw):
    return BertConfig(hidden_size=1024, num_layers=24,
                      num_attention_heads=16, intermediate_size=4096, **kw)
