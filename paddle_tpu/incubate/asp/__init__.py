"""ASP — automatic structured (n:m) sparsity (ref:
``python/paddle/incubate/asp/`` → ``asp.py`` ``prune_model``/``decorate``,
``utils.py`` mask generation, ``supported_layer_list.py``).

The reference targets Ampere sparse-tensor-core 2:4 kernels; on TPU there
is no 2:4 hardware path, but the PRUNING WORKFLOW is hardware-neutral and
kept at API parity: generate n:m masks for supported weights, apply them,
and guarantee sparsity across optimizer steps by re-masking after every
update (``OptimizerWithSparsityGuarantee``). Masked weights stay exactly
zero, so XLA-level value-based optimizations and model-compression
pipelines work unchanged.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...nn.layer.layers import Layer
from ...nn.layer.common import Linear

__all__ = ["calculate_density", "check_sparsity", "create_mask",
           "prune_model", "decorate", "reset_excluded_layers",
           "set_excluded_layers", "OptimizerWithSparsityGuarantee",
           "add_supported_layer"]

_excluded: set = set()
# user-extended supported layer types (ref supported_layer_list.py:84):
# type -> optional custom pruning fn(weight_nparray, m, n, func_name,
# param_name) -> mask ndarray
_extra_supported: dict = {}


def add_supported_layer(layer, pruning_func=None):
    """Register a layer TYPE (or its class name) whose 2-D weights ASP
    should prune, optionally with a custom mask function (ref
    ``supported_layer_list.py:84``)."""
    if isinstance(layer, str):
        name = layer
    elif isinstance(layer, type) and issubclass(layer, Layer):
        name = layer.__name__
    else:
        raise TypeError(
            "layer must be a Layer subclass or its class-name string")
    _extra_supported[name] = pruning_func
_masks: dict = {}  # param name -> mask array


def calculate_density(x) -> float:
    """ref ``asp.py calculate_density``: nonzero fraction."""
    arr = np.asarray(getattr(x, "_data", x))
    return float((arr != 0).sum()) / max(arr.size, 1)


def create_mask(tensor, func_name="mask_1d", n=2, m=4):
    """n:m mask along the LAST axis: keep the ``n`` largest |w| of every
    contiguous group of ``m`` (ref ``utils.py create_mask / get_mask_1d``).
    Trailing remainder (last-dim not divisible by m) is kept dense."""
    arr = np.asarray(getattr(tensor, "_data", tensor), np.float32)
    last = arr.shape[-1]
    groups = last // m
    mask = np.ones_like(arr, dtype=np.float32)
    if groups == 0:
        return mask
    head = arr[..., :groups * m].reshape(arr.shape[:-1] + (groups, m))
    order = np.argsort(-np.abs(head), axis=-1)
    keep = np.zeros_like(head)
    np.put_along_axis(keep, order[..., :n], 1.0, axis=-1)
    mask[..., :groups * m] = keep.reshape(arr.shape[:-1] + (groups * m,))
    return mask


def check_sparsity(tensor, func_name="check_mask_1d", n=2, m=4) -> bool:
    """True iff every complete m-group along the last axis has at most n
    nonzeros (ref ``utils.py check_sparsity``)."""
    arr = np.asarray(getattr(tensor, "_data", tensor))
    last = arr.shape[-1]
    groups = last // m
    if groups == 0:
        return True
    head = arr[..., :groups * m].reshape(-1, m)
    return bool(((head != 0).sum(-1) <= n).all())


def set_excluded_layers(param_names, main_program=None):
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def _supported_params(model: Layer):
    for lname, sub in model.named_sublayers(include_self=True):
        tname = type(sub).__name__
        if not isinstance(sub, Linear) and tname not in _extra_supported:
            continue
        custom = _extra_supported.get(tname)
        for pname, p in sub.named_parameters(include_sublayers=False):
            if pname != "weight":
                continue
            full = f"{lname}.{pname}" if lname else pname
            if full in _excluded or lname in _excluded:
                continue
            if p.ndim == 2 and p.shape[-1] % 4 == 0:
                yield full, p, custom


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every supported weight and register them so
    ``decorate``-wrapped optimizers re-assert sparsity after each step
    (ref ``asp.py prune_model``). Returns {param_name: mask}."""
    out = {}
    for name, p, custom_fn in _supported_params(model):
        if custom_fn is not None:
            # user mask fn contract (ref supported_layer_list.py:84):
            # (weight_nparray, m, n, func_name, param_name) -> mask
            import numpy as _np
            mask = _np.asarray(custom_fn(_np.asarray(p._data), m, n,
                                         mask_algo, name))
        else:
            mask = create_mask(p, func_name=mask_algo, n=n, m=m)
        p._data = p._data * jnp.asarray(mask, dtype=p._data.dtype)
        if with_mask:
            _masks[p.name] = mask  # keyed by tensor name (optimizer view)
        out[name] = mask
    return out


class OptimizerWithSparsityGuarantee:
    """ref ``asp.py OptimizerWithSparsityGuarantee``: after every inner
    step, multiply the registered masks back in — dense gradient flow,
    guaranteed-sparse weights."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def step(self):
        self._optimizer.step()
        for p in self._optimizer._parameter_list:
            mask = _masks.get(p.name)
            if mask is not None:
                p._data = p._data * jnp.asarray(mask, dtype=p._data.dtype)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self._optimizer.clear_grad()
        return None, []

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)
