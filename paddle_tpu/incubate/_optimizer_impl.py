"""Incubate optimizers (ref: ``python/paddle/incubate/optimizer/``):
LookAhead, ModelAverage, DistributedFusedLamb.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..optimizer.adam import Lamb
from ..tensor import Tensor

__all__ = ["LookAhead", "ModelAverage", "DistributedFusedLamb"]


class LookAhead:
    """ref ``incubate/optimizer/lookahead.py LookAhead``: keep slow
    weights; every ``k`` inner steps move them ``alpha`` toward the fast
    weights and reset the fast weights to them."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        # snapshot slow weights NOW (ref lookahead.py: slow params start
        # at the initial fast params, so the first sync really pulls the
        # fast weights back toward the start)
        self._slow = {p.name: jnp.copy(p._data)
                      for p in inner_optimizer._parameter_list}
        self._steps = 0

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        self.inner_optimizer.step()
        self._steps += 1
        if self._steps % self.k:
            return
        for p in self._parameter_list:
            slow = self._slow.get(p.name)
            if slow is None:  # param added after construction
                slow = p._data
            slow = slow + self.alpha * (p._data - slow)
            self._slow[p.name] = slow
            # distinct buffer: the inner optimizer's fused update DONATES
            # p._data, which must never alias the stored slow weights
            p._data = jnp.copy(slow).astype(p._data.dtype)

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, []

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        out = self.inner_optimizer.state_dict()
        for name, arr in self._slow.items():
            out[f"{name}_lookahead_slow"] = Tensor(arr)
        out["lookahead_steps"] = self._steps
        return out

    def set_state_dict(self, state):
        state = dict(state)
        self._steps = int(state.pop("lookahead_steps", 0))
        for key in list(state):
            if key.endswith("_lookahead_slow"):
                v = state.pop(key)
                self._slow[key[:-len("_lookahead_slow")]] = (
                    v._data if isinstance(v, Tensor) else jnp.asarray(v))
        self.inner_optimizer.set_state_dict(state)

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)


class ModelAverage:
    """ref ``incubate/optimizer/modelaverage.py``: maintain a WINDOWED
    running average of parameters; ``apply()`` swaps it in for
    evaluation, ``restore()`` swaps back.

    Windowing follows the reference's block scheme (sum_1/sum_2 rotation):
    two accumulator blocks; when the current block reaches the effective
    window — ``clip(average_window_rate * num_updates,
    min_average_window, max_average_window)``, the reference's window
    rule — it displaces the previous one, so the average always covers
    roughly the most recent one-to-two windows instead of the whole
    run."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters or [])
        self._rate = float(average_window_rate)
        self._min_window = int(min_average_window)
        self._max_window = int(max_average_window)
        zeros = {p.name: jnp.zeros_like(p._data.astype(jnp.float32))
                 for p in self._params}
        self._sum_cur = dict(zeros)
        self._sum_old = {k: v for k, v in zeros.items()}
        self._cnt_cur = 0
        self._cnt_old = 0
        self._total = 0
        self._backup = None

    def _window(self):
        return int(max(min(self._rate * max(self._total, 1),
                           self._max_window), self._min_window))

    def step(self):
        if self._cnt_cur >= self._window():
            self._sum_old = self._sum_cur
            self._cnt_old = self._cnt_cur
            self._sum_cur = {p.name: jnp.zeros_like(
                p._data.astype(jnp.float32)) for p in self._params}
            self._cnt_cur = 0
        for p in self._params:
            self._sum_cur[p.name] = self._sum_cur[p.name] + p._data.astype(
                jnp.float32)
        self._cnt_cur += 1
        self._total += 1

    def apply(self, executor=None, need_restore=True):
        total = self._cnt_cur + self._cnt_old
        if not total:
            return
        self._backup = {p.name: p._data for p in self._params}
        for p in self._params:
            avg = (self._sum_cur[p.name] + self._sum_old[p.name]) / total
            p._data = avg.astype(p._data.dtype)

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[p.name]
        self._backup = None

    def minimize(self, loss, **kw):
        self.step()


class DistributedFusedLamb(Lamb):
    """ref ``incubate/optimizer/distributed_fused_lamb.py``: the
    reference fuses LAMB updates into custom CUDA kernels and shards the
    optimizer state across ranks. TPU-native: XLA fuses the whole
    tree-update already (optimizer._update is one compiled kernel), and
    the ZeRO machinery partitions state over the ``sharding`` mesh axis —
    so this is Lamb with stage-2 sharding on by default."""

    def __init__(self, *args, **kwargs):
        kwargs.pop("clip_after_allreduce", None)
        kwargs.pop("is_grad_scaled_by_nranks", None)
        kwargs.pop("use_master_param_norm", None)
        kwargs.pop("gradient_accumulation_steps", None)
        kwargs.pop("use_master_acc_grad", None)
        super().__init__(*args, **kwargs)
        self._group_sharded_level = "os_g"


# ref python/paddle/incubate/optimizer/__init__.py exposes LBFGS here
# (it later graduated to paddle.optimizer; one implementation serves both)
from ..optimizer.lbfgs import LBFGS  # noqa: E402,F401
