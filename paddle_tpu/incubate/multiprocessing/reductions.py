"""Tensor reductions for multiprocessing (ref:
``python/paddle/incubate/multiprocessing/reductions.py``).

Lifetime model (file_system-strategy semantics): the PRODUCER owns each
shm segment and unlinks all of its segments at interpreter exit;
consumers attach, copy, and close. This makes pickles re-loadable (a
segment survives multiple loads) and bounds leaks to the producer's
lifetime even when a queued pickle is never delivered — the failure
mode the reference's torch-style tracker exists for.
"""
from __future__ import annotations

import atexit
import os
from multiprocessing.reduction import ForkingPickler

import numpy as np

from ...tensor import Parameter, Tensor

_COUNTER = [0]
_OWNED: list[str] = []
_MIN_SHM_BYTES = 1 << 16  # small tensors ride plain bytes
_STRATEGY = ["bytes"]  # "bytes" (default) | "file_system"


def set_sharing_strategy(strategy):
    """"file_system" ships tensor payloads through named POSIX shm
    (zero pickle-copy, producer-lifetime segments); the default "bytes"
    embeds them in the pickle (normal lifetime, no /dev/shm growth for
    long-running queue producers)."""
    if strategy not in ("bytes", "file_system"):
        raise ValueError(f"unknown sharing strategy {strategy!r}")
    _STRATEGY[0] = strategy


def get_sharing_strategy():
    return _STRATEGY[0]


@atexit.register
def _cleanup_owned():
    try:
        from ...core import shm_unlink
    except Exception:
        return
    for name in _OWNED:
        try:
            shm_unlink(name)
        except Exception:
            pass
    _OWNED.clear()


def _restore(t, is_param, stop_gradient, name):
    if is_param:
        p = Parameter(t._data, trainable=not stop_gradient, name=name)
        return p
    t.stop_gradient = stop_gradient
    t.name = name
    return t


def _rebuild_from_shm(shm_name, shape, dtype_str, nbytes, is_param,
                      stop_gradient, name):
    from ...core import ShmSegment
    seg = ShmSegment.attach(shm_name, nbytes)
    arr = np.frombuffer(seg.buffer(), dtype=np.dtype(dtype_str),
                        count=int(np.prod(shape)) if shape else 1)
    out = Tensor(arr.reshape(shape).copy())
    seg.close()  # producer unlinks at its exit; pickle stays loadable
    return _restore(out, is_param, stop_gradient, name)


def _rebuild_from_bytes(buf, shape, dtype_str, is_param, stop_gradient,
                        name):
    arr = np.frombuffer(buf, dtype=np.dtype(dtype_str)).reshape(shape)
    return _restore(Tensor(arr.copy()), is_param, stop_gradient, name)


def _reduce_tensor(t: Tensor):
    a = np.asarray(t._data)
    meta = (isinstance(t, Parameter), bool(t.stop_gradient), t.name)
    try:
        from ...core import ShmSegment, shm_available
        if _STRATEGY[0] == "file_system" and shm_available() \
                and a.nbytes >= _MIN_SHM_BYTES and not a.dtype.hasobject:
            _COUNTER[0] += 1
            shm_name = f"/ptmp_{os.getpid()}_{_COUNTER[0]}"
            seg = ShmSegment.create(shm_name, a.nbytes)
            # record ownership IMMEDIATELY: a copy failure below must
            # still be unlinked at exit, not orphaned forever
            _OWNED.append(shm_name)
            dst = np.frombuffer(seg.buffer(), dtype=a.dtype, count=a.size)
            np.copyto(dst.reshape(a.shape), a)
            seg.close()
            return (_rebuild_from_shm,
                    (shm_name, a.shape, a.dtype.str, a.nbytes) + meta)
    except Exception:
        pass
    return (_rebuild_from_bytes, (a.tobytes(), a.shape, a.dtype.str) + meta)


def init_reductions():
    ForkingPickler.register(Tensor, _reduce_tensor)
    ForkingPickler.register(Parameter, _reduce_tensor)
