"""``paddle.incubate.multiprocessing`` (ref:
``python/paddle/incubate/multiprocessing/``): the stdlib
multiprocessing surface plus Tensor pickling-over-shared-memory.

The reference registers ForkingPickler reductions that move tensor
storage into file-system shared memory. Here the same hook serializes a
Tensor's array into a named POSIX shm segment via the native core
(``core/native/shm.cc``, the DataLoader's transport) and rebuilds a
device array on the consumer side; falls back to plain bytes when shm
is unavailable.
"""
from multiprocessing import *  # noqa: F401,F403
import multiprocessing as _mp

from .reductions import init_reductions

__all__ = []

init_reductions()


def get_context(method=None):
    return _mp.get_context(method)
