"""``paddle.incubate.multiprocessing`` (ref:
``python/paddle/incubate/multiprocessing/``): the stdlib
multiprocessing surface plus Tensor pickling-over-shared-memory.

The reference registers ForkingPickler reductions that move tensor
storage into file-system shared memory. Here the same hook serializes a
Tensor's array into a named POSIX shm segment via the native core
(``core/native/shm.cc``, the DataLoader's transport) and rebuilds a
device array on the consumer side; falls back to plain bytes when shm
is unavailable.
"""
from multiprocessing import *  # noqa: F401,F403

from .reductions import (  # noqa: F401
    init_reductions, set_sharing_strategy, get_sharing_strategy,
)

__all__ = []

init_reductions()
