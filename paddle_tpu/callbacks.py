"""``paddle.callbacks`` namespace parity."""
from .hapi.callbacks import (Callback, ProgBarLogger, ModelCheckpoint,  # noqa: F401
                             LRScheduler, EarlyStopping, VisualDL,
                             ReduceLROnPlateau, WandbCallback)
