"""``paddle.linalg`` namespace (ref: ``python/paddle/linalg.py``)."""
from .ops.linalg import (  # noqa: F401
    matmul, mm, bmm, dot, mv, dist, norm, cond, cholesky, cholesky_solve,
    qr, svd, svdvals, pca_lowrank, lu, lu_unpack, inverse, det, slogdet,
    solve, triangular_solve, lstsq, matrix_power, matrix_rank, eig, eigh,
    eigvals, eigvalsh, pinv, cross, multi_dot, corrcoef, cov, einsum,
    householder_product, matrix_exp, vecdot, vector_norm, matrix_norm,
    cdist,
)

inv = inverse
