"""``paddle.fft`` (ref: ``python/paddle/fft.py``): discrete Fourier
transforms over ``jnp.fft`` — XLA lowers these to its native FFT (TPU has a
dedicated FFT path), replacing the reference's cuFFT/pocketfft backends
(``paddle/phi/kernels/funcs/fft.cc``)."""
from __future__ import annotations

import jax.numpy as jnp

from .ops.op_utils import unary
from .tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
    "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn", "fftfreq",
    "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    # paddle: "backward" | "ortho" | "forward" — same contract as numpy
    return norm or "backward"


def _mk1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return unary(lambda d: jfn(d, n=n, axis=axis, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


def _mk2(jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return unary(lambda d: jfn(d, s=s, axes=axes, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


def _mkn(jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return unary(lambda d: jfn(d, s=s, axes=axes, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


fft = _mk1(jnp.fft.fft)
ifft = _mk1(jnp.fft.ifft)
rfft = _mk1(jnp.fft.rfft)
irfft = _mk1(jnp.fft.irfft)
hfft = _mk1(jnp.fft.hfft)
ihfft = _mk1(jnp.fft.ihfft)
fft2 = _mk2(jnp.fft.fft2)
ifft2 = _mk2(jnp.fft.ifft2)
rfft2 = _mk2(jnp.fft.rfft2)
irfft2 = _mk2(jnp.fft.irfft2)
fftn = _mkn(jnp.fft.fftn)
ifftn = _mkn(jnp.fft.ifftn)
rfftn = _mkn(jnp.fft.rfftn)
irfftn = _mkn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(int(n), d=float(d)))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(int(n), d=float(d)))


def fftshift(x, axes=None, name=None):
    return unary(lambda d: jnp.fft.fftshift(d, axes=axes), x,
                 name="fftshift")


def ifftshift(x, axes=None, name=None):
    return unary(lambda d: jnp.fft.ifftshift(d, axes=axes), x,
                 name="ifftshift")
