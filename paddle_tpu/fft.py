"""``paddle.fft`` (ref: ``python/paddle/fft.py``): discrete Fourier
transforms over ``jnp.fft`` — XLA lowers these to its native FFT (TPU has a
dedicated FFT path), replacing the reference's cuFFT/pocketfft backends
(``paddle/phi/kernels/funcs/fft.cc``)."""
from __future__ import annotations

import jax.numpy as jnp

from .ops.op_utils import unary
from .tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
    "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn", "fftfreq",
    "rfftfreq", "fftshift", "ifftshift", "hfft2", "ihfft2", "hfftn",
    "ihfftn",
]


def _norm(norm):
    # paddle: "backward" | "ortho" | "forward" — same contract as numpy
    return norm or "backward"


def _mk1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return unary(lambda d: jfn(d, n=n, axis=axis, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


def _mk2(jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return unary(lambda d: jfn(d, s=s, axes=axes, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


def _mkn(jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return unary(lambda d: jfn(d, s=s, axes=axes, norm=_norm(norm)), x,
                     name=jfn.__name__)
    return op


fft = _mk1(jnp.fft.fft)
ifft = _mk1(jnp.fft.ifft)
rfft = _mk1(jnp.fft.rfft)
irfft = _mk1(jnp.fft.irfft)
hfft = _mk1(jnp.fft.hfft)
ihfft = _mk1(jnp.fft.ihfft)
fft2 = _mk2(jnp.fft.fft2)
ifft2 = _mk2(jnp.fft.ifft2)
rfft2 = _mk2(jnp.fft.rfft2)
irfft2 = _mk2(jnp.fft.irfft2)
fftn = _mkn(jnp.fft.fftn)
ifftn = _mkn(jnp.fft.ifftn)
rfftn = _mkn(jnp.fft.rfftn)
irfftn = _mkn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(int(n), d=float(d)))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(int(n), d=float(d)))


def fftshift(x, axes=None, name=None):
    return unary(lambda d: jnp.fft.fftshift(d, axes=axes), x,
                 name="fftshift")


def ifftshift(x, axes=None, name=None):
    return unary(lambda d: jnp.fft.ifftshift(d, axes=axes), x,
                 name="ifftshift")


def _hermitian_axes(d, s, axes):
    """numpy/scipy axes defaulting: all dims when neither s nor axes is
    given, the last len(s) dims when only s is."""
    if axes is not None:
        axes = tuple(axes)
    elif s is not None:
        axes = tuple(range(-len(s), 0))
    else:
        axes = tuple(range(-d.ndim, 0))
    if s is not None and len(s) != len(axes):
        raise ValueError("fft: s and axes must have the same length")
    return axes


def _hfftn_impl(d, s, axes, norm):
    """Hermitian N-d FFT (ref ``fft.py:1123 hfftn``): full complex FFT over
    the leading axes, Hermitian (real-output) FFT over the last. jnp has no
    hfftn — compose it; separate-axis FFTs commute."""
    axes = _hermitian_axes(d, s, axes)
    if len(axes) > 1:
        d = jnp.fft.fftn(d, s=tuple(s[:-1]) if s is not None else None,
                         axes=axes[:-1], norm=norm)
    return jnp.fft.hfft(d, n=s[-1] if s is not None else None,
                        axis=axes[-1], norm=norm)


def _ihfftn_impl(d, s, axes, norm):
    axes = _hermitian_axes(d, s, axes)
    out = jnp.fft.ihfft(d, n=s[-1] if s is not None else None,
                        axis=axes[-1], norm=norm)
    if len(axes) > 1:
        out = jnp.fft.ifftn(out, s=tuple(s[:-1]) if s is not None else None,
                            axes=axes[:-1], norm=norm)
    return out


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return unary(lambda d: _hfftn_impl(d, s, axes, _norm(norm)), x,
                 name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return unary(lambda d: _ihfftn_impl(d, s, axes, _norm(norm)), x,
                 name="ihfftn")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    axes = tuple(axes) if axes is not None else None
    return unary(lambda d: _hfftn_impl(d, s, axes, _norm(norm)), x,
                 name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    axes = tuple(axes) if axes is not None else None
    return unary(lambda d: _ihfftn_impl(d, s, axes, _norm(norm)), x,
                 name="ihfft2")
