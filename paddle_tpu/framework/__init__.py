"""Core framework layer: dtypes, devices, flags, RNG.

The TPU-native analog of the reference's ``paddle/phi/core`` +
``paddle/fluid/platform`` glue, minus everything XLA subsumes (allocators,
streams, kernel registry).
"""
from .dtype import (  # noqa: F401
    DType, dtype, convert_dtype, to_jax_dtype, get_default_dtype,
    set_default_dtype, default_jax_dtype, iinfo, finfo,
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
)
from .flags import set_flags, get_flags, define_flag, flag  # noqa: F401
from .string_tensor import (  # noqa: F401
    StringTensor, SelectedRows, strings_lower, strings_upper)
from .random import (  # noqa: F401
    seed, get_rng_state, set_rng_state, default_generator, next_key,
    RNGStatesTracker, get_tracker, rng_state_guard,
)
from .device import (  # noqa: F401
    Place, CPUPlace, TPUPlace, CUDAPlace, CustomPlace, XPUPlace,
    CUDAPinnedPlace,
    set_device, get_device, get_all_devices, device_count,
    is_compiled_with_cuda, is_compiled_with_rocm, is_compiled_with_xpu,
    is_compiled_with_tpu, is_compiled_with_cinn,
    is_compiled_with_custom_device, device_guard, get_jax_device,
)
