"""Lazy parameter initialization (ref: ``python/paddle/fluid/lazy_init.py:91
LazyGuard``).

Under ``with LazyGuard():`` layer construction defers the (potentially
expensive, device-touching) initializer: parameters are created with
zero-filled host placeholders plus a recorded ``(initializer, shape, dtype)``
closure, and ``param.initialize()`` runs the real init later. On TPU this
matters at scale — constructing a model inside the guard performs no device
allocation, so a sharded init (or a checkpoint load) can place parameters
directly with their final sharding.
"""
from __future__ import annotations

import threading

__all__ = ["LazyGuard", "lazy_init_active"]

_tls = threading.local()


def lazy_init_active() -> bool:
    return getattr(_tls, "depth", 0) > 0


class LazyGuard:
    def __enter__(self):
        _tls.depth = getattr(_tls, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.depth -= 1
        return False
