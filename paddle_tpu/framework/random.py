"""Random state management.

The reference uses per-device stateful Philox generators
(``paddle/phi/core/generator.cc``; python ``paddle.seed``). On TPU the
idiomatic design is counter-based splitting of a functional threefry key —
stateful mutation does not compose with jit/pjit.

Design: a global `Generator` holds a root jax PRNG key and a fold counter.
Eager ops draw fresh keys by folding the counter (cheap, traceable); jitted
code should thread keys explicitly or use `rng_state_guard` /
`RNGStatesTracker` (the TP-dropout tracker, re-designed from
``fleet/meta_parallel/parallel_layers/random.py:34 RNGStatesTracker``).
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np

__all__ = ["seed", "get_rng_state", "set_rng_state", "default_generator",
           "Generator", "next_key", "RNGStatesTracker", "get_tracker",
           "rng_state_guard"]


class Generator:
    """Counter-based key generator; `state` is (seed, counter).

    The root key is materialised lazily: importing the framework must never
    initialise the PJRT backend (the reference has the same rule — device
    init happens on first op, ``paddle/fluid/platform/init.cc``).
    """

    def __init__(self, seed_: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed_)

    def manual_seed(self, seed_: int):
        self._seed = int(seed_) & 0xFFFFFFFFFFFFFFFF
        self._root = None  # lazily created on first draw
        self._counter = 0
        return self

    def _root_key(self):
        if self._root is None:
            from . import flags as _flags
            impl = _flags.flag("prng_impl") or None
            self._root = jax.random.key(self._seed, impl=impl)
        return self._root

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self, n: int | None = None):
        """Draw `n` fresh keys (or one if n is None).

        Under a functional trace (to_static / jitted train step), keys fold
        from the per-call key tracer instead of host state, so dropout masks
        are fresh on every call of the compiled program instead of baked in
        as constants.
        """
        tk = _trace_key_state()
        if tk is not None:
            c = tk["counter"]
            tk["counter"] += (n or 1)
            root = tk["key"]
        else:
            with self._lock:
                c = self._counter
                self._counter += (n or 1)
                root = self._root_key()
        if n is None:
            return jax.random.fold_in(root, c)
        return jax.vmap(lambda i: jax.random.fold_in(root, i))(
            np.arange(c, c + n, dtype=np.uint32))

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state):
        self._seed = int(state[0])
        self._root = None
        self._counter = int(state[1])


default_generator = Generator(0)

_trace_tls = threading.local()


def _trace_key_state():
    stack = getattr(_trace_tls, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def trace_key_scope(key):
    """While active, `next_key()` folds from `key` (a traced PRNG key)."""
    stack = getattr(_trace_tls, "stack", None)
    if stack is None:
        stack = _trace_tls.stack = []
    stack.append({"key": key, "counter": 0})
    try:
        yield
    finally:
        stack.pop()


def seed(s: int) -> Generator:
    """``paddle.seed`` equivalent: reseed the global generator."""
    return default_generator.manual_seed(s)


def next_key(n=None):
    return default_generator.next_key(n)


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state):
    default_generator.set_state(state)


@contextlib.contextmanager
def rng_state_guard(seed_: int):
    """Run a block under a temporary deterministic RNG state."""
    old = default_generator.get_state()
    default_generator.manual_seed(seed_)
    try:
        yield
    finally:
        default_generator.set_state(old)


class RNGStatesTracker:
    """Named RNG states for model-parallel dropout.

    Re-design of the reference tracker
    (``fleet/meta_parallel/parallel_layers/random.py:34``): tensor-parallel
    regions need dropout masks that *differ* across mp ranks for partitioned
    activations but *match* for replicated ones. Here each named state is an
    independent fold counter over a seed; the mp axis offset is folded in at
    mesh-aware call sites.
    """

    def __init__(self):
        self.states_: dict[str, Generator] = {}

    def reset(self):
        self.states_.clear()

    def add(self, name: str, seed_: int):
        if name in self.states_:
            raise ValueError(f"rng state {name} already exists")
        self.states_[name] = Generator(seed_)

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        global default_generator
        if name not in self.states_:
            raise ValueError(f"rng state {name} does not exist")
        prev = default_generator
        default_generator = self.states_[name]
        try:
            yield
        finally:
            default_generator = prev

    def get_states_tracker(self):
        return {k: g.get_state() for k, g in self.states_.items()}

    def set_states_tracker(self, states):
        for k, s in states.items():
            self.states_.setdefault(k, Generator(0)).set_state(s)


_tracker = RNGStatesTracker()


def get_tracker() -> RNGStatesTracker:
    return _tracker


def get_cuda_rng_state():
    """Parity shim (ref ``framework.py get_cuda_rng_state``): there are no
    CUDA generators on this build — returns an empty list, the reference's
    behavior on a CPU-only build."""
    return []


def set_cuda_rng_state(state_list):
    """Parity shim: accepts and ignores an empty state list."""
    if state_list:
        raise ValueError(
            "set_cuda_rng_state: no CUDA generators on a TPU build")
