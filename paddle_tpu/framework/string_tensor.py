"""StringTensor + SelectedRows analogs (SURVEY §2 "Tensor types" row).

ref: ``paddle/phi/core/string_tensor.h`` + the strings kernels
(``paddle/phi/kernels/strings/case_convert_kernel.h`` lower/upper) and
``paddle/phi/core/selected_rows.h``.

TPU stance: strings never touch the accelerator (the reference's string
kernels are CPU-only too) — StringTensor is a host container with the
case-conversion ops the reference ships. SelectedRows is the row-sparse
(rows, values, height) gradient container; its TPU-native update path is
``distributed.ps.row_sparse_apply`` (dedup + OOB-dropped scatter).
"""
from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "SelectedRows", "strings_lower", "strings_upper"]


class StringTensor:
    """Host tensor of variable-length unicode strings."""

    def __init__(self, data, name=None):
        self._data = np.asarray(data, dtype=object)
        self.name = name

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return "pstring"  # the reference's dtype name

    def numpy(self):
        return self._data

    def lower(self, use_utf8_encoding=True):
        return StringTensor(np.vectorize(
            lambda s: s.lower(), otypes=[object])(self._data))

    def upper(self, use_utf8_encoding=True):
        return StringTensor(np.vectorize(
            lambda s: s.upper(), otypes=[object])(self._data))

    def __getitem__(self, idx):
        out = self._data[idx]
        return out if isinstance(out, str) else StringTensor(out)

    def __len__(self):
        return len(self._data)

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"


def strings_lower(x, use_utf8_encoding=True, name=None):
    """ref ``paddle/phi/kernels/strings/case_convert_kernel.h`` lower."""
    return (x if isinstance(x, StringTensor) else StringTensor(x)).lower()


def strings_upper(x, use_utf8_encoding=True, name=None):
    return (x if isinstance(x, StringTensor) else StringTensor(x)).upper()


class SelectedRows:
    """Row-sparse value container (ref ``selected_rows.h``): ``rows`` are
    the touched indices into a ``[height, ...]`` dense space, ``value``
    holds one slice per row. The analog of the PS sparse-grad format; see
    ``distributed.ps.row_sparse_apply`` for the lazy update."""

    def __init__(self, rows, value, height):
        import jax.numpy as jnp
        self.rows = jnp.asarray(np.asarray(rows, np.int32))
        self.value = jnp.asarray(value)
        self.height = int(height)

    @property
    def shape(self):
        return [self.height] + list(self.value.shape[1:])

    def to_dense(self):
        """Scatter-ADD duplicates into the dense form (reference merge
        semantics for gradient SelectedRows)."""
        import jax.numpy as jnp
        dense = jnp.zeros((self.height,) + tuple(self.value.shape[1:]),
                          self.value.dtype)
        return dense.at[self.rows].add(self.value)

    def apply_to(self, weight, update_fn):
        """Row-lazy update of ``weight`` with these values (dedup +
        OOB-drop scatter via ``distributed.ps.row_sparse_apply``)."""
        from ..distributed.ps import row_sparse_apply
        new_w, _ = row_sparse_apply(weight, self.rows, self.value,
                                    update_fn)
        return new_w
