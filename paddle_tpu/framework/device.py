"""Device and place management.

TPU-native replacement for the reference's device layer:
 - ``phi::Place`` / ``CUDAPlace`` / ``CPUPlace`` (``paddle/phi/common/place.h``)
 - ``phi::DeviceManager`` enumeration (``paddle/phi/backends/device_manager.h:128``)
 - ``paddle.set_device`` (``python/paddle/device/__init__.py``)

On TPU, device enumeration comes from the PJRT client via ``jax.devices()``;
"place" maps to a jax Device, and a `device_guard` maps to
``jax.default_device``. There are no user-visible streams: XLA owns ordering
(the reference's stream/event machinery — ``paddle/phi/backends/stream.h`` —
is subsumed by the compiler's async scheduling).
"""
from __future__ import annotations

import contextlib

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "CUDAPlace", "CustomPlace", "XPUPlace",
    "CUDAPinnedPlace",
    "set_device", "get_device", "get_all_devices", "device_count",
    "is_compiled_with_cuda", "is_compiled_with_rocm", "is_compiled_with_xpu",
    "is_compiled_with_tpu", "is_compiled_with_cinn",
    "is_compiled_with_custom_device", "device_guard", "get_jax_device",
]


class Place:
    """Base place: (device_type, index)."""

    device_type = "undefined"

    def __init__(self, index: int = 0):
        self._index = int(index)

    def get_device_id(self) -> int:
        return self._index

    def __repr__(self):
        return f"Place({self.device_type}:{self._index})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and other.device_type == self.device_type
                and other._index == self._index)

    def __hash__(self):
        return hash((self.device_type, self._index))

    def jax_device(self):
        devs = [d for d in jax.devices() if _platform_matches(d, self.device_type)]
        if not devs:
            if self.device_type == "cpu":
                return jax.devices("cpu")[0]
            raise RuntimeError(f"No {self.device_type} device available")
        return devs[min(self._index, len(devs) - 1)]

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_gpu_place(self):
        return False

    def is_tpu_place(self):
        return self.device_type == "tpu"


def _platform_matches(dev, device_type: str) -> bool:
    plat = dev.platform
    if device_type == "tpu":
        # 'axon'-tunnelled TPUs report a vendor platform name; treat any
        # non-cpu accelerator as the tpu place.
        return plat != "cpu"
    return plat == device_type


class CPUPlace(Place):
    device_type = "cpu"

    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    device_type = "tpu"


# Parity aliases: reference scripts say CUDAPlace; on this framework the
# accelerator is the TPU.
class CUDAPlace(TPUPlace):
    pass


class XPUPlace(TPUPlace):
    pass


class CUDAPinnedPlace(Place):
    """Host staging-memory place. On TPU the analog of CUDA pinned memory
    is the host side of the PJRT transfer path; kept for API parity."""
    device_type = "cuda_pinned"

    def __init__(self):
        super().__init__(0)


class CustomPlace(Place):
    def __init__(self, device_type="tpu", index=0):
        super().__init__(index)
        self.device_type = device_type


_current_device: str | None = None


def _default_device_str() -> str:
    try:
        d = jax.devices()[0]
        return "cpu" if d.platform == "cpu" else f"tpu:{d.id}"
    except RuntimeError:
        return "cpu"


def set_device(device: str):
    """``paddle.set_device``: 'cpu', 'tpu', 'tpu:0' (also accepts 'gpu' as a
    parity alias for the accelerator)."""
    global _current_device
    device = device.lower().replace("gpu", "tpu").replace("xpu", "tpu")
    if device in ("tpu", "cpu"):
        device += ":0"
    kind, _, idx = device.partition(":")
    if kind not in ("cpu", "tpu"):
        raise ValueError(f"Unknown device {device!r}")
    place = CPUPlace() if kind == "cpu" else TPUPlace(int(idx or 0))
    jax.config.update("jax_default_device", place.jax_device())
    _current_device = f"{kind}:{idx or 0}" if kind != "cpu" else "cpu"
    return place


def get_device() -> str:
    return _current_device or _default_device_str()


def get_all_devices():
    return [("cpu" if d.platform == "cpu" else f"tpu:{d.id}") for d in jax.devices()]


def device_count() -> int:
    return len(jax.devices())


def get_jax_device(place=None):
    if place is None:
        dev = get_device()
        kind, _, idx = dev.partition(":")
        place = CPUPlace() if kind == "cpu" else TPUPlace(int(idx or 0))
    elif isinstance(place, str):
        kind, _, idx = place.lower().replace("gpu", "tpu").partition(":")
        place = CPUPlace() if kind == "cpu" else TPUPlace(int(idx or 0))
    return place.jax_device()


@contextlib.contextmanager
def device_guard(device: str):
    """Scoped default device (ref: ``paddle.static.device_guard``)."""
    prev = get_device()
    set_device(device)
    try:
        yield
    finally:
        set_device(prev)


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # XLA plays CINN's role and is always present.
    return True


def is_compiled_with_tpu() -> bool:
    return any(d.platform != "cpu" for d in jax.devices())


def is_compiled_with_custom_device(device_type: str = "tpu") -> bool:
    return True
