"""Global flag registry.

TPU-native re-design of the reference's gflags-backed flag system
(``paddle/phi/core/flags.cc`` defines 91 ``PHI_DEFINE_EXPORTED_*`` flags;
Python access via ``paddle.set_flags/get_flags`` in
``python/paddle/fluid/framework.py:7472``).

Here the registry is a typed python dict with an env-var override layer
(``FLAGS_<name>``), mirrored into the native runtime core when it is loaded
(see ``paddle_tpu/core``). Flags that only make sense on CUDA are accepted
but inert, so reference-style scripts keep working.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable

__all__ = ["define_flag", "set_flags", "get_flags", "flag"]


@dataclass
class _Flag:
    name: str
    default: Any
    type: type
    help: str
    on_change: Callable[[Any], None] | None = None


_registry: dict[str, _Flag] = {}
_values: dict[str, Any] = {}
_lock = threading.Lock()


def _parse(typ, raw):
    if typ is bool:
        if isinstance(raw, str):
            return raw.lower() in ("1", "true", "yes", "on")
        return bool(raw)
    return typ(raw)


def define_flag(name: str, default, help: str = "", type_=None,
                on_change: Callable[[Any], None] | None = None):
    typ = type_ or type(default)
    with _lock:
        _registry[name] = _Flag(name, default, typ, help, on_change)
        env = os.environ.get(f"FLAGS_{name}")
        _values[name] = _parse(typ, env) if env is not None else default
    return _values[name]


def set_flags(flags: dict):
    """``paddle.set_flags`` equivalent. Unknown flags are registered on the
    fly (the reference tolerates vendor-specific flags the same way)."""
    if not isinstance(flags, dict):
        raise TypeError("set_flags expects a dict of {name: value}")
    for name, value in flags.items():
        name = name.removeprefix("FLAGS_")
        with _lock:
            f = _registry.get(name)
            if f is None:
                f = _Flag(name, value, type(value), "(runtime-defined)")
                _registry[name] = f
            _values[name] = _parse(f.type, value)
        if f.on_change is not None:
            f.on_change(_values[name])
        _mirror_to_native(name, _values[name])


def _mirror_to_native(name, value):
    """Mirror into the native core's flag table (paddle/phi/core/flags.cc
    analog) so C++ components can consult flags without re-entering Python.
    Only when the lib is already loaded — set_flags must never trigger the
    g++ build; ``core._load`` replays the full table on first load."""
    try:
        import sys
        _core = sys.modules.get("paddle_tpu.core")
        if _core is not None and _core._lib is not None:
            _core._lib.pt_flag_set(name.encode(), str(value).encode())
    except Exception:
        pass


def get_flags(flags) -> dict:
    """``paddle.get_flags`` equivalent; accepts a name or list of names."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        key = name.removeprefix("FLAGS_")
        if key not in _values:
            raise ValueError(f"Unknown flag: {name}")
        out[name] = _values[key]
    return out


def flag(name: str, default=None):
    """Fast internal read."""
    return _values.get(name, default)


# -- Core flags (TPU-meaningful subset of paddle/phi/core/flags.cc) ---------
define_flag("check_nan_inf", False,
            "Scan op outputs for NaN/Inf after every eager op "
            "(ref: paddle/fluid/eager/nan_inf_utils.cc)")
define_flag("benchmark", False, "Synchronize after every eager op for timing")
define_flag("check_varlen", False,
            "Validate cu_seqlens inside traced flash_attn_unpadded calls "
            "via a host callback (debug mode)")
define_flag("prng_impl", "rbg",
            "PRNG implementation for framework-drawn keys: 'rbg' uses the "
            "TPU-native XLA rng_bit_generator (threefry-seeded; measured "
            "~60ms/step cheaper than 'threefry2x32' for GPT-345M dropout "
            "masks on v5e), 'threefry2x32' is jax's default splittable RNG")
def _set_matmul_precision(v):
    import jax
    jax.config.update("jax_default_matmul_precision",
                      None if v in ("default", "") else v)


define_flag("tpu_matmul_precision", "default",
            "XLA matmul precision: default (bf16 passes on MXU) | "
            "float32|tensorfloat32|bfloat16_3x|highest "
            "(ref analog: FLAGS_gemm_use_half_precision_compute_type)",
            on_change=_set_matmul_precision)
define_flag("log_level", 0, "VLOG-style verbosity for the python runtime")
define_flag("use_stream_safe_cuda_allocator", True, "inert on TPU (parity)")
define_flag("allocator_strategy", "auto_growth", "inert on TPU (parity)")
define_flag("eager_delete_tensor_gb", 0.0, "inert on TPU (parity)")
define_flag("cudnn_deterministic", False,
            "Maps to XLA deterministic ops on TPU where applicable")
define_flag("embedding_deterministic", 0, "inert on TPU (parity)")
define_flag("flash_attn_version", 2, "Select pallas flash-attention version")
define_flag("use_pallas_kernels", True,
            "Use hand-written Pallas TPU kernels where available "
            "(flash attention etc.); pure-XLA fallback otherwise")
define_flag("flash_min_seq", 512,
            "Minimum q-sequence length for SDPA to pick the Pallas flash "
            "kernel; below it XLA's fused O(S^2) attention is faster "
            "(measured on v5e: BERT s=128 808 vs 750 seq/s)")
