"""paddle.save / paddle.load (ref: ``python/paddle/framework/io.py:278
_pickle_save``).

Same contract as the reference: pickle container with tensors converted to
numpy; loads back into Tensors. Safety: loading uses a restricted
unpickler that only reconstructs numpy arrays and builtin containers.
"""
from __future__ import annotations

import io
import os
import pickle

import numpy as np

from ..tensor import Tensor

__all__ = ["save", "load"]


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._data),
                "name": obj.name, "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_saveable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient",
                                                          True))
            return t
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_saveable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    """``paddle.save``: state_dicts, nested containers, single tensors."""
    if hasattr(obj, "state_dict") and not isinstance(obj, dict):
        obj = obj.state_dict()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


class _SafeUnpickler(pickle.Unpickler):
    _ALLOWED = {
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy", "ndarray"),
        ("numpy", "dtype"),
        ("numpy.core.multiarray", "scalar"),
        ("numpy._core.multiarray", "scalar"),
        ("collections", "OrderedDict"),
        ("ml_dtypes", "bfloat16"),
        ("ml_dtypes", "float8_e4m3fn"),
        ("ml_dtypes", "float8_e5m2"),
    }

    def find_class(self, module, name):
        if (module, name) in self._ALLOWED or module.startswith("numpy"):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"paddle_tpu.load refuses to unpickle {module}.{name}; "
            "checkpoints may only contain arrays and containers")


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = _SafeUnpickler(f).load()
    return _from_saveable(obj, return_numpy=return_numpy)
