"""Dtype system.

TPU-native re-design of the reference dtype machinery
(``paddle/phi/common/data_type.h`` and the pybind'd ``paddle.dtype`` enum).
Rather than an enum dispatched through a kernel registry, dtypes here are thin
wrappers over numpy/jax dtypes that flow straight into XLA.

Notes on TPU policy:
 - 64-bit types are *accepted* at the API surface but canonicalised to their
   32-bit counterparts (JAX x64-disabled mode), which is the right default on
   TPU: the MXU natively computes in bf16/f32 and 64-bit integer indexing is
   never needed for on-chip shapes.
 - ``bfloat16`` is a first-class citizen (the AMP default), unlike the
   reference where fp16 is primary (``python/paddle/amp/auto_cast.py``).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

__all__ = [
    "DType", "dtype", "convert_dtype", "to_jax_dtype",
    "get_default_dtype", "set_default_dtype", "iinfo", "finfo",
    "bool_", "uint8", "int8", "int16", "int32", "int64",
    "float16", "bfloat16", "float32", "float64", "complex64", "complex128",
    "float8_e4m3fn", "float8_e5m2",
]


class DType:
    """A framework dtype: named wrapper around a canonical numpy dtype.

    Mirrors the surface of the reference's ``paddle.dtype`` (repr, equality
    with strings / numpy dtypes) without the VarType protobuf enum behind it.
    """

    __slots__ = ("name", "np_dtype", "_canonical_name")

    def __init__(self, name: str, np_dtype, canonical_name: str | None = None):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        # what this dtype canonicalises to under TPU (x64-disabled) policy
        self._canonical_name = canonical_name or name

    # -- identity ----------------------------------------------------------
    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            return other in (self.name, f"paddle_tpu.{self.name}")
        try:
            return np.dtype(other) == self.np_dtype
        except TypeError:
            return NotImplemented

    def __ne__(self, other):
        r = self.__eq__(other)
        return r if r is NotImplemented else not r

    # -- properties --------------------------------------------------------
    @property
    def itemsize(self):
        return self.np_dtype.itemsize

    @property
    def is_floating_point(self):
        return np.issubdtype(self.np_dtype, np.floating) or self.np_dtype in (
            np.dtype(ml_dtypes.bfloat16),
            np.dtype(ml_dtypes.float8_e4m3fn),
            np.dtype(ml_dtypes.float8_e5m2),
        )

    @property
    def is_integer(self):
        return np.issubdtype(self.np_dtype, np.integer)

    @property
    def is_complex(self):
        return np.issubdtype(self.np_dtype, np.complexfloating)


# Canonical dtype singletons ------------------------------------------------
bool_ = DType("bool", np.bool_)
uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64, canonical_name="int32")
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", ml_dtypes.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64, canonical_name="float32")
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128, canonical_name="complex64")
float8_e4m3fn = DType("float8_e4m3fn", ml_dtypes.float8_e4m3fn)
float8_e5m2 = DType("float8_e5m2", ml_dtypes.float8_e5m2)

_ALL = [bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
        float64, complex64, complex128, float8_e4m3fn, float8_e5m2]
_BY_NAME = {d.name: d for d in _ALL}
_BY_NAME["bool_"] = bool_
_BY_NAME["float"] = float32
_BY_NAME["double"] = float64
_BY_NAME["half"] = float16
_BY_NAME["int"] = int32
_BY_NAME["long"] = int64
_BY_NP = {}
for _d in _ALL:
    _BY_NP.setdefault(_d.np_dtype, _d)


def dtype(obj) -> DType:
    """Coerce anything dtype-like to a framework DType."""
    if isinstance(obj, DType):
        return obj
    if isinstance(obj, str):
        name = obj.replace("paddle_tpu.", "").replace("paddle.", "")
        if name in _BY_NAME:
            return _BY_NAME[name]
    npd = np.dtype(obj)
    if npd in _BY_NP:
        return _BY_NP[npd]
    raise TypeError(f"Unsupported dtype: {obj!r}")


def to_jax_dtype(obj):
    """Framework/str/numpy dtype -> jax-canonical numpy dtype (x64 policy)."""
    d = dtype(obj)
    return np.dtype(_BY_NAME[d._canonical_name].np_dtype)


def convert_dtype(obj) -> str:
    """Dtype-like -> canonical name string (reference:
    ``python/paddle/fluid/data_feeder.py convert_dtype``)."""
    return dtype(obj).name


_default_dtype = float32


def set_default_dtype(d):
    """Set default floating dtype for tensor creation (``paddle.set_default_dtype``)."""
    global _default_dtype
    d = dtype(d)
    if not d.is_floating_point:
        raise TypeError(f"default dtype must be floating point, got {d}")
    _default_dtype = d


def get_default_dtype() -> str:
    return _default_dtype.name


def default_jax_dtype():
    return to_jax_dtype(_default_dtype)


class iinfo:
    """``paddle.iinfo`` equivalent."""

    def __init__(self, d):
        info = np.iinfo(dtype(d).np_dtype)
        self.min, self.max, self.bits = info.min, info.max, info.bits
        self.dtype = convert_dtype(d)


class finfo:
    """``paddle.finfo`` equivalent (supports bfloat16/fp8 via ml_dtypes)."""

    def __init__(self, d):
        info = ml_dtypes.finfo(dtype(d).np_dtype)
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.smallest_normal)
        self.resolution = float(info.resolution)
        self.bits = info.bits
        self.dtype = convert_dtype(d)


def result_dtype(*arrs):
    """Promotion helper used by binary ops."""
    return jnp.result_type(*arrs)
