"""``paddle_tpu.io`` — data pipeline (ref: ``python/paddle/io/``)."""
from .dataset import (Dataset, IterableDataset, TensorDataset,  # noqa: F401
                      ComposeDataset, ChainDataset, Subset, ConcatDataset,
                      random_split)
from .sampler import (Sampler, SequenceSampler, RandomSampler,  # noqa: F401
                      WeightedRandomSampler, BatchSampler,
                      DistributedBatchSampler, SubsetRandomSampler)
from .dataloader import DataLoader, default_collate_fn, get_worker_info  # noqa: F401
