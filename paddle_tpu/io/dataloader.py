"""DataLoader (ref: ``python/paddle/io/reader.py:218 DataLoader``,
workers in ``io/dataloader/worker.py``).

TPU-native design notes:
 - the hot path feeds the device asynchronously: batches are assembled as
   numpy on host threads/processes and handed to jax, whose dispatch is
   already async — so a small prefetch depth hides host latency behind
   device compute (the reference's DoubleBufferReader equivalent).
 - multiprocess workers use a process pool with a reorder buffer, matching
   the reference's out-of-order-collect + in-order-deliver semantics.
 - batch assembly (stacking samples) is delegated to the native C++ core
   when available (csrc/collate.cc) — the reference's C++ BlockingQueue+
   collate analog — with a numpy fallback.
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue
import threading

import numpy as np

from ..tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, dataset=None, seed=0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """Stack a list of samples into batch arrays (ref:
    ``io/dataloader/collate.py``)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        try:
            from ..core import fast_stack
            return fast_stack(batch)
        except Exception:
            return np.stack(batch)
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        out = [default_collate_fn(list(col)) for col in transposed]
        return type(sample)(out) if not isinstance(sample, tuple) else \
            tuple(out)
    return np.asarray(batch)


def _to_tensor_tree(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_tensor_tree(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


_SHM_TAG = "__ptshm__"


def _shm_pack(data, name):
    """Write the batch's numpy leaves into one shared-memory segment
    (native shm.cc; ref mmap_allocator.cc): the queue then carries only
    metadata instead of pickled tensor bytes. Returns the tagged payload
    or None when shm is unavailable / there is nothing big to ship."""
    try:
        from ..core import ShmSegment, shm_available
        if not shm_available():
            return None
    except Exception:
        return None
    leaves = []

    def skel(obj):
        # object/structured dtypes can't ride raw bytes — leave them on
        # the pickle path
        if isinstance(obj, np.ndarray) and obj.nbytes > 0 \
                and not obj.dtype.hasobject:
            leaves.append(obj)
            return (_SHM_TAG, "leaf", len(leaves) - 1)
        if isinstance(obj, dict):
            return {k: skel(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            out = [skel(v) for v in obj]
            return out if isinstance(obj, list) else tuple(out)
        return obj

    skeleton = skel(data)
    if not leaves:
        return None

    def align(o):
        return (o + 63) & ~63

    total, offs = 0, []
    for a in leaves:
        offs.append(align(total))
        total = offs[-1] + a.nbytes
    try:
        seg = ShmSegment.create(name, max(total, 1))
    except Exception:
        return None
    buf = seg.buffer()
    meta = []
    for a, off in zip(leaves, offs):
        # copy straight into the mapping (no tobytes() intermediate)
        dst = np.frombuffer(buf, dtype=a.dtype, count=a.size,
                            offset=off).reshape(a.shape)
        np.copyto(dst, a)
        meta.append((a.shape, a.dtype.str, off, a.nbytes))
    seg.close()  # producer unmaps; the segment lives until consumer unlinks
    return (_SHM_TAG, name, max(total, 1), skeleton, meta)


def _is_shm_payload(data) -> bool:
    """Structural check for the 5-tuple produced by ``_shm_pack``."""
    return (isinstance(data, tuple) and len(data) == 5
            and data[0] == _SHM_TAG)


def _shm_discard(payload):
    """Unlink a packed segment without reading it (early-exit cleanup:
    POSIX shm outlives the process, so unconsumed payloads must not leak
    into /dev/shm)."""
    try:
        from ..core import shm_unlink
        shm_unlink(payload[1])
    except Exception:
        pass


def _shm_unpack(payload):
    """Rebuild the batch tree from a packed segment, then unlink it."""
    from ..core import ShmSegment
    _, name, total, skeleton, meta = payload
    seg = ShmSegment.attach(name, total)
    buf = seg.buffer()
    arrs = [np.frombuffer(buf, dtype=np.dtype(dt), count=n // np.dtype(
        dt).itemsize, offset=off).reshape(shape).copy()
        for shape, dt, off, n in meta]

    def rebuild(obj):
        if isinstance(obj, tuple) and len(obj) == 3 and obj[0] == _SHM_TAG \
                and obj[1] == "leaf":
            return arrs[obj[2]]
        if isinstance(obj, dict):
            return {k: rebuild(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            out = [rebuild(v) for v in obj]
            return out if isinstance(obj, list) else tuple(out)
        return obj

    out = rebuild(skeleton)
    seg.close()
    seg.unlink()
    return out


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id,
                 num_workers, seed, use_shared_memory=False):
    import os
    _worker_info.info = WorkerInfo(worker_id, num_workers, dataset, seed)
    np.random.seed((seed + worker_id) % (2 ** 31))
    while True:
        item = index_queue.get()
        if item is None:
            break
        batch_id, indices = item
        try:
            samples = [dataset[i] for i in indices]
            data = collate_fn(samples)
            if use_shared_memory:
                payload = _shm_pack(
                    data, f"/ptdl_{os.getpid()}_{batch_id}")
                if payload is not None:
                    data = payload
            data_queue.put((batch_id, data, None))
        except Exception as e:  # propagate worker errors to the main process
            import traceback
            data_queue.put((batch_id, None, traceback.format_exc()))


def _get_checked(data_queue, workers, timeout, last_sent=None):
    """Blocking queue get that notices dead workers instead of hanging
    forever (the reference's ``_DataLoaderIterMultiProcess`` does the same
    via ``_check_worker_status``: a crashed/killed worker raises
    'DataLoader worker exited unexpectedly' rather than deadlocking the
    training loop).  ``last_sent`` maps worker id -> last batch index
    dispatched to it, so the error names exactly which batch died with
    the worker (a poisoned sample is findable from the message alone)."""
    import time as _time
    deadline = (_time.monotonic() + timeout) if timeout else None
    while True:
        tick = 1.0
        if deadline is not None:
            tick = min(1.0, max(0.01, deadline - _time.monotonic()))
        try:
            return data_queue.get(timeout=tick)
        except queue.Empty:
            dead = [(wid, w) for wid, w in enumerate(workers)
                    if not w.is_alive()]
            if dead:
                detail = "; ".join(
                    f"worker {wid} (pid {w.pid}) exitcode {w.exitcode}, "
                    f"last dispatched batch index "
                    f"{(last_sent or {}).get(wid, 'none')}"
                    for wid, w in dead)
                raise RuntimeError(
                    f"DataLoader worker(s) exited unexpectedly: {detail}")
            if deadline is not None and _time.monotonic() >= deadline:
                raise RuntimeError(
                    f"DataLoader timed out after {timeout}s waiting for a "
                    f"batch")


def _timed_iter(it, tel, tr):
    """Wrap a batch iterator, reporting how long the consumer waited on
    each ``next()`` (input-pipeline stall time) to telemetry and, as a
    ``data_wait`` phase span, to the step tracer. Only installed while
    one of the two is enabled — the disabled path hands the raw
    iterator through."""
    import time as _time
    while True:
        t0 = _time.perf_counter_ns()
        try:
            batch = next(it)
        except StopIteration:
            return
        t1 = _time.perf_counter_ns()
        if tel.enabled:
            tel.data_wait((t1 - t0) / 1e9)
        if tr.enabled:
            tr.phase_record("data_wait", t0, t1)
        yield batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.num_workers = max(0, int(num_workers))
        self.use_shared_memory = bool(use_shared_memory)
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = max(1, prefetch_factor)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.return_list = return_list
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
        # batches actually handed to the consumer this epoch — the
        # mid-epoch resume cursor. Sampler-side counters run ahead of
        # this by the prefetch depth, so state_dict() trusts only it.
        self._delivered = 0

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._iterable_mode:
            it = self._iter_iterable()
        elif self.num_workers == 0:
            it = self._iter_single()
        else:
            it = self._iter_multiprocess()
        it = self._counted(it)
        from ..observability import get_telemetry
        from ..observability.trace import get_tracer
        tel = get_telemetry()
        tr = get_tracer()
        if not (tel.enabled or tr.enabled):
            return it
        return _timed_iter(it, tel, tr)

    def _counted(self, it):
        # a resumed epoch starts its delivered count at the sampler's
        # skip cursor (absolute position within the epoch); a fresh
        # epoch starts at 0
        self._delivered = getattr(self.batch_sampler, "_resume_skip", 0)
        for batch in it:
            self._delivered += 1
            yield batch

    def state_dict(self):
        """Mid-epoch input-pipeline position, persistable beside the
        model checkpoint (``CheckpointManager.save(...,
        data_state=...)``).  ``cursor`` is the *delivered* batch count
        — prefetch means the sampler itself has already run ahead."""
        sd = {"delivered": self._delivered}
        bs = self.batch_sampler
        if bs is not None and hasattr(bs, "state_dict"):
            s = dict(bs.state_dict())
            s["cursor"] = self._delivered
            sd["sampler"] = s
        return sd

    def load_state_dict(self, state):
        """Restore a :meth:`state_dict`: the next ``__iter__`` resumes
        mid-epoch, skipping already-delivered batches at the *index*
        level (no dataset element is fetched for a skipped batch), so
        the resumed loss trajectory is bit-identical to an
        uninterrupted run — no replayed and no skipped batches."""
        bs = self.batch_sampler
        samp = state.get("sampler")
        if bs is not None and samp is not None \
                and hasattr(bs, "load_state_dict"):
            bs.load_state_dict(samp)
        self._delivered = getattr(bs, "_resume_skip", 0) if bs is not None \
            else 0

    # -- single process with thread prefetch --------------------------------
    def _iter_single(self):
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield _to_tensor_tree(self.dataset[i])
            return
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_factor)
        stop = object()

        def produce():
            try:
                for indices in self.batch_sampler:
                    samples = [self.dataset[i] for i in indices]
                    q.put(self.collate_fn(samples))
            except Exception:
                import traceback
                q.put(RuntimeError(traceback.format_exc()))
            finally:
                q.put(stop)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is stop:
                break
            if isinstance(item, RuntimeError):
                raise item
            yield _to_tensor_tree(item)

    def _iter_iterable(self):
        it = iter(self.dataset)
        if self.batch_size is None:
            for sample in it:
                yield _to_tensor_tree(sample)
            return
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and self.drop_last:
                return
            yield _to_tensor_tree(self.collate_fn(batch))

    # -- multiprocess workers with reorder buffer ---------------------------
    def _iter_multiprocess(self):
        # prefer spawn: the parent holds a live (multithreaded) jax runtime
        # and forking it can deadlock workers. Fall back to fork only when
        # the dataset/collate_fn aren't picklable (locally-defined classes).
        import pickle
        try:
            pickle.dumps((self.dataset, self.collate_fn))
            ctx = mp.get_context("spawn")
        except Exception:
            ctx = mp.get_context("fork")
        index_queues = [ctx.Queue() for _ in range(self.num_workers)]
        data_queue = ctx.Queue()
        seed = np.random.randint(0, 2 ** 31)
        workers = []
        for wid in range(self.num_workers):
            w = ctx.Process(
                target=_worker_loop,
                args=(self.dataset, index_queues[wid], data_queue,
                      self.collate_fn, wid, self.num_workers, seed,
                      self.use_shared_memory),
                daemon=True)
            w.start()
            workers.append(w)
        reorder: dict = {}
        last_sent: dict = {}  # worker id -> last batch index dispatched
        try:
            batches = list(self.batch_sampler)
            n = len(batches)
            next_send = 0
            # pre-fill each worker's queue
            for _ in range(self.prefetch_factor):
                for wid in range(self.num_workers):
                    if next_send < n:
                        index_queues[wid].put((next_send, batches[next_send]))
                        last_sent[wid] = next_send
                        next_send += 1
            next_yield = 0
            while next_yield < n:
                if next_yield in reorder:
                    data = reorder.pop(next_yield)
                    next_yield += 1
                    yield _to_tensor_tree(data)
                    continue
                batch_id, data, err = _get_checked(data_queue, workers,
                                                   self.timeout, last_sent)
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed:\n{err}")
                if _is_shm_payload(data):
                    data = _shm_unpack(data)
                if next_send < n:
                    wid = batch_id % self.num_workers
                    index_queues[wid].put((next_send, batches[next_send]))
                    last_sent[wid] = next_send
                    next_send += 1
                reorder[batch_id] = data
        finally:
            for q_ in index_queues:
                try:
                    q_.put(None)
                except Exception:
                    pass
            # Drain and join interleaved: a worker's queue feeder thread
            # may be blocked flushing a large pickled batch nobody will
            # consume — joining first would time out and terminate() it
            # mid-write, corrupting the queue. POSIX shm outlives the
            # process, so unconsumed tagged payloads must be unlinked,
            # not just dropped. (reorder never holds tagged payloads:
            # they are unpacked before insertion.)
            import time as _time

            def _drain():
                while True:
                    try:
                        _, data, _err = data_queue.get_nowait()
                    except Exception:
                        break
                    if _is_shm_payload(data):
                        _shm_discard(data)

            pending = [w for w in workers]
            deadline = _time.monotonic() + 5
            while pending and _time.monotonic() < deadline:
                _drain()
                for w in pending:
                    w.join(timeout=0.2)
                pending = [w for w in pending if w.is_alive()]
            for w in pending:
                w.terminate()
                w.join(timeout=1)
            _drain()
