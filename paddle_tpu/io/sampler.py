"""Samplers (ref: ``python/paddle/io/dataloader/{sampler,batch_sampler}.py``)."""
from __future__ import annotations

import numpy as np

__all__ = ["Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler", "SubsetRandomSampler", "BatchSampler",
           "DistributedBatchSampler"]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def _rng(self):
        # honor an injected generator (np.random.RandomState /
        # np.random.Generator) so a resumed run can replay the exact
        # sample order; fall back to the global stream like the
        # reference
        return self.generator if self.generator is not None else np.random

    def __iter__(self):
        n = len(self.data_source)
        rng = self._rng()
        if self.replacement:
            if hasattr(rng, "randint"):  # RandomState / np.random
                idx = rng.randint(0, n, self.num_samples)
            else:  # np.random.Generator
                idx = rng.integers(0, n, self.num_samples)
            return iter(idx.tolist())
        perm = rng.permutation(n)[:self.num_samples]
        return iter(perm.tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle
        self._cursor = 0       # index batches handed out this epoch
        self._resume_skip = 0  # batches to drop at the next __iter__

    def __iter__(self):
        skip = self._resume_skip
        self._resume_skip = 0
        self._cursor = skip
        batch = []
        produced = 0
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                produced += 1
                if produced > skip:  # index-level skip: no data fetched
                    self._cursor += 1
                    yield batch
                batch = []
        if batch and not self.drop_last:
            produced += 1
            if produced > skip:
                self._cursor += 1
                yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def state_dict(self):
        """Mid-epoch position; the DataLoader overwrites ``cursor``
        with its *delivered* count (prefetch makes this one run
        ahead)."""
        return {"cursor": self._cursor}

    def load_state_dict(self, state):
        cursor = int(state.get("cursor", 0))
        if cursor >= len(self):  # checkpoint fell on the epoch boundary
            cursor = 0
        self._resume_skip = cursor


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batches (ref: ``distributed_batch_sampler.py``).

    On TPU with GSPMD data parallelism the global batch is usually formed
    once and sharded by the compiler; this sampler exists for per-process
    input pipelines (multi-host) and reference parity.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self._iter_epoch = 0   # epoch of the in-flight permutation
        self._cursor = 0       # index batches handed out this epoch
        self._resume_skip = 0  # batches to drop at the next __iter__
        import math
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        # the permutation is a pure function of the epoch number, so
        # (epoch, cursor) fully determines mid-epoch state — that is
        # what makes state_dict()/load_state_dict() resume bit-exact
        self._iter_epoch = self.epoch
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
            self.epoch += 1
        else:
            indices = list(range(n))
        # pad to make evenly divisible
        indices += indices[:(self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        skip = self._resume_skip
        self._resume_skip = 0
        self._cursor = skip
        # index-level resume: drop whole batches of *indices* — no
        # dataset element is fetched for a skipped batch
        indices = indices[skip * self.batch_size:]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                self._cursor += 1
                yield batch
                batch = []
        if batch and not self.drop_last:
            self._cursor += 1
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch

    def state_dict(self):
        """Mid-epoch resume state: the epoch whose permutation is in
        flight plus the batch cursor (the DataLoader overwrites
        ``cursor`` with its delivered count — sampler-side counting
        runs ahead of the consumer by the prefetch depth)."""
        return {"epoch": self._iter_epoch, "cursor": self._cursor}

    def load_state_dict(self, state):
        epoch = int(state.get("epoch", 0))
        cursor = int(state.get("cursor", 0))
        if cursor >= len(self):  # checkpoint fell on the epoch boundary
            epoch += 1
            cursor = 0
        self.epoch = epoch
        self._iter_epoch = epoch
        self._resume_skip = cursor
