"""Graph-audit rules AUD001+ — jaxpr-level analyses.

Each rule is a class with an ``AUD0xx`` id registered in ``RULES`` and
a ``check(program) -> [Finding]`` method over an
:class:`~.core.AuditProgram`.  The catalog covers the hazard classes
tpu-lint cannot see from source (ROADMAP "remaining hazard classes"):

======  ===================  ==========================================
id      name                 what it catches
======  ===================  ==========================================
AUD001  implicit-reshard     a value constrained to one PartitionSpec
                             re-constrained to a different one through
                             layout-preserving ops — GSPMD must insert
                             an all-to-all / collective-permute the
                             source never spells out; also flags mesh
                             axes outside the ``SpecLayout`` canon
AUD002  amp-precision-leak   f32 ``dot_general``/reductions reachable
                             from bf16 values through an explicit
                             upcast with no accumulation contract —
                             the MXU runs full-precision silently
AUD003  undonated-buffer     a large argument with a same-shaped
                             output it could alias, dead after last
                             read yet not donated — double allocation,
                             byte-weighted via PR 14 memory_analysis
AUD004  host-transfer        callbacks/infeed/outfeed in the program —
                             the IR-level complement of TPU019; an
                             error on the serving request path
AUD005  missed-fusion        clusters the fusion pass should have
                             claimed but did not, with the blocking
                             escape named (``fusion_pass.match_report``)
AUD006  dequant-placement    an int8→float dequantize whose result
                             reaches more than one ``dot_general`` —
                             XLA must materialize the full-precision
                             copy in HBM, forfeiting the int8 memory
                             win; an error in serve programs
======  ===================  ==========================================
"""
from __future__ import annotations

import os
from collections import Counter
from typing import List

import numpy as np

from .core import (AuditProgram, Finding, GraphView, audit_disabled_rules,
                   walk_jaxprs)
from .core import _is_literal as _is_lit

__all__ = ["RULES", "register", "Rule", "default_rules", "rule_catalog"]

RULES = {}


def register(cls):
    RULES[cls.id] = cls
    return cls


class Rule:
    """Base: subclasses set ``id``/``name``/``rationale`` and implement
    ``check``."""

    id = "AUD000"
    name = "base"
    rationale = ""

    def check(self, prog: AuditProgram) -> List[Finding]:
        raise NotImplementedError


def default_rules(select=None):
    """Instantiate the rule set: every registered rule, filtered by an
    explicit ``select`` iterable of ids and the lazily read
    ``PT_AUDIT_DISABLE`` knob."""
    disabled = audit_disabled_rules()
    picked = None if select is None else {s.upper() for s in select}
    if picked is not None:
        unknown = picked - set(RULES)
        if unknown:
            raise KeyError(
                f"unknown rule id(s) {sorted(unknown)} "
                f"(known: {', '.join(sorted(RULES))})")
    out = []
    for rid in sorted(RULES):
        if rid in disabled:
            continue
        if picked is not None and rid not in picked:
            continue
        out.append(RULES[rid]())
    return out


def rule_catalog():
    return [(rid, RULES[rid].name, RULES[rid].rationale)
            for rid in sorted(RULES)]


# ---------------------------------------------------------------------------
# shared jaxpr helpers
# ---------------------------------------------------------------------------
_NARROW = ("bfloat16", "float16")
_WIDE = ("float32", "float64")

# ops that forward a value without changing what a sharding spec or an
# upcast provenance means for it
_LAYOUT_TRANSPARENT = frozenset((
    "reshape", "broadcast_in_dim", "squeeze", "rev", "copy",
    "convert_element_type", "stop_gradient", "slice", "dynamic_slice",
))
_ELEMENTWISE = frozenset((
    "add", "sub", "mul", "div", "neg", "exp", "log", "tanh", "logistic",
    "rsqrt", "sqrt", "abs", "max", "min", "pow", "integer_pow", "sign",
    "erf", "select_n",
))


def _dtype_name(aval) -> str:
    return np.dtype(aval.dtype).name


def _aval_bytes(aval) -> int:
    try:
        return int(np.prod(aval.shape, dtype=np.int64)) * \
            np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def _spec_tuple(spec):
    """PartitionSpec -> hashable normalized tuple (strings/None/tuples)."""
    out = []
    for entry in tuple(spec):
        if isinstance(entry, (list, tuple)):
            out.append(tuple(str(a) for a in entry))
        else:
            out.append(None if entry is None else str(entry))
    return tuple(out)


def _spec_str(tup) -> str:
    def one(e):
        if e is None:
            return "None"
        if isinstance(e, tuple):
            return "(" + ",".join(e) + ")"
        return e
    return "P(" + ",".join(one(e) for e in tup) + ")"


def _spec_axes(tup):
    axes = set()
    for e in tup:
        if isinstance(e, tuple):
            axes.update(e)
        elif e is not None:
            axes.add(e)
    return axes


# ---------------------------------------------------------------------------
# AUD001 — implicit reshard
# ---------------------------------------------------------------------------
@register
class ImplicitReshard(Rule):
    id = "AUD001"
    name = "implicit-reshard"
    rationale = ("two conflicting sharding constraints on one value "
                 "chain make GSPMD materialize an all-to-all or "
                 "collective-permute the source never wrote; specs "
                 "should agree with the SpecLayout canon")

    # walking back through these cannot change which spec the value
    # wants — a transpose/dot DOES, so the walk stops there
    _WALK = _LAYOUT_TRANSPARENT | _ELEMENTWISE

    def _canon_axes(self):
        from ...distributed.auto_parallel.spec_layout import SpecLayout
        lo = SpecLayout()
        return {lo.data_axis, lo.fsdp_axis, lo.tp_axis, lo.sep_axis}

    def check(self, prog: AuditProgram) -> List[Finding]:
        findings: List[Finding] = []
        canon = None
        for jaxpr, _path in walk_jaxprs(prog.jaxpr):
            cons = [(i, e) for i, e in enumerate(jaxpr.eqns)
                    if e.primitive.name == "sharding_constraint"]
            if not cons:
                continue
            g = GraphView(jaxpr)
            spec_of = {}                      # constrained outvar -> spec
            for i, eqn in cons:
                spec = getattr(eqn.params.get("sharding"), "spec", None)
                if spec is None:
                    continue
                spec_of[eqn.outvars[0]] = _spec_tuple(spec)
            for i, eqn in cons:
                spec = getattr(eqn.params.get("sharding"), "spec", None)
                if spec is None:
                    continue
                here = _spec_tuple(spec)
                if canon is None:
                    canon = self._canon_axes()
                alien = _spec_axes(here) - canon
                if alien:
                    findings.append(Finding(
                        rule=self.id, severity="warning",
                        program=prog.name,
                        provenance=f"axis[{','.join(sorted(alien))}]",
                        message=(f"constraint {_spec_str(here)} uses mesh "
                                 f"axes {sorted(alien)} outside the "
                                 "SpecLayout canon (dp/sharding/mp/sep) — "
                                 "a retargeted mesh must rename through "
                                 "SpecLayout, not ad-hoc specs")))
                seen, frontier, hops = set(), [eqn.invars[0]], 0
                while frontier and hops < 64:
                    hops += 1
                    v = frontier.pop()
                    if id(v) in seen:
                        continue
                    seen.add(id(v))
                    up = spec_of.get(v)
                    if up is not None and up != here \
                            and tuple(v.aval.shape) == \
                            tuple(eqn.invars[0].aval.shape):
                        findings.append(Finding(
                            rule=self.id, severity="error",
                            program=prog.name,
                            provenance=(f"reshard[{_spec_str(up)}->"
                                        f"{_spec_str(here)}]"
                                        f"{v.aval.str_short()}"),
                            message=(f"value constrained to {_spec_str(up)} "
                                     f"is re-constrained to "
                                     f"{_spec_str(here)} with only "
                                     "layout-preserving ops between — "
                                     "GSPMD inserts an implicit "
                                     "all-to-all/collective-permute "
                                     "here")))
                        continue
                    pi = g.producer(v)
                    if pi is None:
                        continue
                    peqn = g.eqns[pi]
                    if peqn.primitive.name == "sharding_constraint" or \
                            peqn.primitive.name in self._WALK:
                        frontier.extend(
                            iv for iv in peqn.invars
                            if hasattr(iv, "aval") and not _is_lit(iv)
                            and (g.producer(iv) is not None
                                 or iv in spec_of))
        return findings


# ---------------------------------------------------------------------------
# AUD002 — AMP precision leak
# ---------------------------------------------------------------------------
@register
class AmpPrecisionLeak(Rule):
    id = "AUD002"
    name = "amp-precision-leak"
    rationale = ("an f32 dot_general fed by explicit bf16→f32 upcasts "
                 "runs the MXU at full precision; the sanctioned form "
                 "is bf16 operands with preferred_element_type=f32. "
                 "A dedicated upcast feeding one wide reduction whose "
                 "result never narrows again is the same leak on the "
                 "reduction path")

    _REDUCES = frozenset(("reduce_sum", "reduce_max", "reduce_min",
                          "reduce_prod"))

    @staticmethod
    def _upcast_from_narrow(g: GraphView, v, max_hops: int = 16):
        """Name of the narrow dtype this wide value was explicitly
        upcast from (walking layout-preserving ops), else None."""
        hops = 0
        while hops < max_hops:
            hops += 1
            pi = g.producer(v)
            if pi is None:
                return None
            eqn = g.eqns[pi]
            prim = eqn.primitive.name
            if prim == "convert_element_type":
                src = eqn.invars[0]
                if hasattr(src, "aval") and \
                        _dtype_name(src.aval) in _NARROW and \
                        _dtype_name(v.aval) in _WIDE:
                    return _dtype_name(src.aval)
                v = src
                continue
            if prim in _LAYOUT_TRANSPARENT:
                v = eqn.invars[0]
                continue
            return None
        return None

    def check(self, prog: AuditProgram) -> List[Finding]:
        findings: List[Finding] = []
        for jaxpr, _path in walk_jaxprs(prog.jaxpr):
            g = None
            for eqn in jaxpr.eqns:
                prim = eqn.primitive.name
                if prim == "dot_general":
                    lhs, rhs = eqn.invars[0], eqn.invars[1]
                    if not (hasattr(lhs, "aval") and hasattr(rhs, "aval")):
                        continue
                    if _dtype_name(lhs.aval) not in _WIDE and \
                            _dtype_name(rhs.aval) not in _WIDE:
                        continue
                    if g is None:
                        g = GraphView(jaxpr)
                    src = None
                    for op in (lhs, rhs):
                        if _dtype_name(op.aval) in _WIDE:
                            src = self._upcast_from_narrow(g, op)
                            if src:
                                break
                    if src:
                        findings.append(Finding(
                            rule=self.id, severity="error",
                            program=prog.name,
                            provenance=(f"dot_general[{lhs.aval.str_short()}"
                                        f"x{rhs.aval.str_short()}<-{src}]"),
                            message=(f"wide dot_general fed by an explicit "
                                     f"{src} upcast — keep operands {src} "
                                     "and set preferred_element_type for "
                                     "the f32 accumulation contract")))
                elif prim in self._REDUCES:
                    opnd = eqn.invars[0]
                    if not hasattr(opnd, "aval") or \
                            _dtype_name(opnd.aval) not in _WIDE:
                        continue
                    if g is None:
                        g = GraphView(jaxpr)
                    pi = g.producer(opnd)
                    if pi is None:
                        continue
                    peqn = g.eqns[pi]
                    if peqn.primitive.name != "convert_element_type":
                        continue
                    src = peqn.invars[0]
                    if not hasattr(src, "aval") or \
                            _dtype_name(src.aval) not in _NARROW:
                        continue
                    # a shared upcast is a deliberate f32 island (LN
                    # stats etc.); the leak is the dedicated upcast
                    # whose single purpose is this reduction
                    if g.sole_consumer(peqn.outvars[0]) is None:
                        continue
                    out = eqn.outvars[0]
                    sc = g.sole_consumer(out)
                    if sc is not None and \
                            g.eqns[sc].primitive.name == \
                            "convert_element_type" and \
                            _dtype_name(g.eqns[sc].outvars[0].aval) \
                            in _NARROW:
                        continue  # accumulate-then-narrow: contract held
                    findings.append(Finding(
                        rule=self.id, severity="warning",
                        program=prog.name,
                        provenance=(f"{prim}[{opnd.aval.str_short()}"
                                    f"<-{_dtype_name(src.aval)}]"),
                        message=(f"{prim} over a dedicated "
                                 f"{_dtype_name(src.aval)}→"
                                 f"{_dtype_name(opnd.aval)} upcast whose "
                                 "wide result never narrows again — "
                                 "either narrow the result or drop the "
                                 "upcast")))
        return findings


# ---------------------------------------------------------------------------
# AUD003 — donation audit
# ---------------------------------------------------------------------------
def _donation_min_bytes() -> int:
    """Lazy PT_AUDIT_DONATION_MIN_BYTES knob (default 1 MiB)."""
    try:
        return int(os.environ.get("PT_AUDIT_DONATION_MIN_BYTES",
                                  str(1 << 20)))
    except ValueError:
        return 1 << 20


@register
class UndonatedBuffer(Rule):
    id = "AUD003"
    name = "undonated-buffer"
    rationale = ("an argument with a same-shaped same-dtype output it "
                 "could alias, yet not donated, forces XLA to hold "
                 "both buffers live across the program — state "
                 "threading (params in → params out) must donate")

    def check(self, prog: AuditProgram) -> List[Finding]:
        jaxpr = getattr(prog.jaxpr, "jaxpr", prog.jaxpr)
        min_bytes = _donation_min_bytes()
        out_budget = Counter()
        for ov in jaxpr.outvars:
            if hasattr(ov, "aval") and hasattr(ov.aval, "shape"):
                out_budget[(tuple(ov.aval.shape),
                            _dtype_name(ov.aval))] += 1
        # donated args claim their aliasing opportunity first
        for i, iv in enumerate(jaxpr.invars):
            if i in prog.donated and hasattr(iv, "aval"):
                sig = (tuple(iv.aval.shape), _dtype_name(iv.aval))
                if out_budget.get(sig, 0) > 0:
                    out_budget[sig] -= 1
        candidates = [(i, iv) for i, iv in enumerate(jaxpr.invars)
                      if i not in prog.donated and hasattr(iv, "aval")
                      and _aval_bytes(iv.aval) >= min_bytes]
        # biggest buffers claim the remaining aliases first: the report
        # leads with the bytes that matter
        candidates.sort(key=lambda p: -_aval_bytes(p[1].aval))
        arg_total = (prog.memory or {}).get("argument", 0)
        findings = []
        for i, iv in candidates:
            sig = (tuple(iv.aval.shape), _dtype_name(iv.aval))
            if out_budget.get(sig, 0) <= 0:
                continue
            out_budget[sig] -= 1
            nbytes = _aval_bytes(iv.aval)
            ctx = (f" (program argument footprint "
                   f"{arg_total / 2**20:.1f} MiB)") if arg_total else ""
            findings.append(Finding(
                rule=self.id, severity="warning", program=prog.name,
                provenance=(f"undonated[{prog.arg_name(i)}:"
                            f"{iv.aval.str_short()}]"),
                message=(f"argument {prog.arg_name(i)} "
                         f"({iv.aval.str_short()}, "
                         f"{nbytes / 2**20:.1f} MiB) has a same-shaped "
                         "output it could alias but is not donated — "
                         "XLA holds both buffers live" + ctx),
                nbytes=nbytes))
        return findings


# ---------------------------------------------------------------------------
# AUD004 — host transfer / request-path effects
# ---------------------------------------------------------------------------
@register
class HostTransfer(Rule):
    id = "AUD004"
    name = "host-transfer"
    rationale = ("callbacks/infeed/outfeed round-trip through the host "
                 "every execution; on the serving request path that is "
                 "a per-token stall — the IR-level complement of "
                 "tpu-lint TPU019")

    _HOST_PRIMS = frozenset(("pure_callback", "io_callback",
                             "debug_callback", "infeed", "outfeed"))

    def check(self, prog: AuditProgram) -> List[Finding]:
        severity = "error" if prog.kind == "serve" else "warning"
        findings = []
        for jaxpr, path in walk_jaxprs(prog.jaxpr):
            for eqn in jaxpr.eqns:
                prim = eqn.primitive.name
                if prim not in self._HOST_PRIMS:
                    continue
                cb = eqn.params.get("callback")
                cb_name = "" if cb is None else \
                    (getattr(cb, "__name__", "") or type(cb).__name__)
                where = f" inside {path}" if path else ""
                res = eqn.outvars[0].aval.str_short() \
                    if eqn.outvars and hasattr(eqn.outvars[0], "aval") \
                    else "()"
                findings.append(Finding(
                    rule=self.id, severity=severity, program=prog.name,
                    provenance=f"{prim}[{res}]",
                    message=(f"{prim}"
                             + (f" ({cb_name})" if cb_name else "")
                             + f"{where} forces a host round-trip every "
                             "execution"
                             + (" — on the serving request path this "
                                "stalls every token"
                                if prog.kind == "serve" else ""))))
        return findings


# ---------------------------------------------------------------------------
# AUD006 — dequant placement
# ---------------------------------------------------------------------------
@register
class DequantPlacement(Rule):
    id = "AUD006"
    name = "dequant-placement"
    rationale = ("an int8→float convert_element_type feeding more than "
                 "one dot_general forces XLA to materialize the "
                 "dequantized copy in HBM and keep it live across every "
                 "consumer — the int8 storage win is forfeited exactly "
                 "where it was supposed to pay; dequantize per use site "
                 "(one convert, one dot) so the upcast fuses into the "
                 "dot it feeds, the w8a16_matmul_reference form")

    _QUANT = frozenset(("int8", "uint8", "int4", "uint4"))
    # ops a dequantized value flows through without the copy stopping
    # being "the dequantized copy" — the scale multiply and gathers of
    # the reference kernels live here
    _FOLLOW = _LAYOUT_TRANSPARENT | _ELEMENTWISE | frozenset((
        "transpose", "concatenate", "gather", "dynamic_slice"))

    def _dot_fanout(self, g: GraphView, v, max_nodes: int = 256) -> int:
        """Distinct dot_generals reachable from ``v`` through
        value-forwarding ops."""
        dots, seen, frontier, n = set(), set(), [v], 0
        while frontier and n < max_nodes:
            n += 1
            u = frontier.pop()
            if id(u) in seen:
                continue
            seen.add(id(u))
            for ci in g.consumers.get(u, ()):
                if ci == g.OUT:
                    continue
                eqn = g.eqns[ci]
                prim = eqn.primitive.name
                if prim == "dot_general":
                    dots.add(ci)
                elif prim in self._FOLLOW:
                    frontier.extend(eqn.outvars)
        return len(dots)

    def check(self, prog: AuditProgram) -> List[Finding]:
        severity = "error" if prog.kind == "serve" else "warning"
        findings: List[Finding] = []
        for jaxpr, path in walk_jaxprs(prog.jaxpr):
            g = None
            for eqn in jaxpr.eqns:
                if eqn.primitive.name != "convert_element_type":
                    continue
                src, out = eqn.invars[0], eqn.outvars[0]
                if not (hasattr(src, "aval") and hasattr(out, "aval")):
                    continue
                if _dtype_name(src.aval) not in self._QUANT:
                    continue
                if not np.issubdtype(np.dtype(out.aval.dtype),
                                     np.floating):
                    continue
                if g is None:
                    g = GraphView(jaxpr)
                dots = self._dot_fanout(g, out)
                if dots <= 1:
                    continue
                where = f" inside {path}" if path else ""
                findings.append(Finding(
                    rule=self.id, severity=severity, program=prog.name,
                    provenance=(f"dequant[{src.aval.str_short()}->"
                                f"{_dtype_name(out.aval)}x{dots}]"),
                    message=(f"dequantized {src.aval.str_short()} feeds "
                             f"{dots} dot_generals{where} — XLA holds "
                             "the full-precision copy live across all "
                             "of them; dequantize per dot (one convert "
                             "per use) so the upcast fuses into the "
                             "dot's operand read")))
        return findings


# ---------------------------------------------------------------------------
# AUD005 — missed fusion
# ---------------------------------------------------------------------------
@register
class MissedFusion(Rule):
    id = "AUD005"
    name = "missed-fusion"
    rationale = ("a cluster the fusion pass matches but never rewrote "
                 "is a silent perf cliff: either the pass was skipped "
                 "for this program, or one escaping value broke "
                 "closure — the blocking eqn is named either way")

    def check(self, prog: AuditProgram) -> List[Finding]:
        if not prog.fusion_expected:
            return []
        from ...ops import fusion_pass
        jaxpr = getattr(prog.jaxpr, "jaxpr", prog.jaxpr)
        # top level only, exactly the scope wrap() rewrites — counting
        # sub-jaxpr clusters would indict the pass for remat bodies it
        # never claims by design
        clusters, near = fusion_pass.match_report(jaxpr)
        eligible = Counter(cl.pattern for cl in clusters)
        findings = []
        for pattern in sorted(eligible):
            n, done = eligible[pattern], prog.fusion_rewrites.get(pattern, 0)
            if done < n:
                findings.append(Finding(
                    rule=self.id, severity="warning", program=prog.name,
                    provenance=f"missed[{pattern}]",
                    message=(f"{n - done} fusable {pattern} cluster(s) "
                             f"matched but only {done} rewritten — the "
                             "fusion pass fell back or was bypassed for "
                             "this program")))
        for cl, blocker in near:
            if eligible.get(cl.pattern, 0) > 0:
                # the pattern does fuse elsewhere in this program; the
                # leftover partial matches are recompute copies the
                # pass skips by design
                continue
            findings.append(Finding(
                rule=self.id, severity="warning", program=prog.name,
                provenance=f"nearmiss[{cl.pattern}]",
                message=(f"cluster matched {cl.pattern} but failed "
                         f"closure: {blocker}")))
        return findings
