"""Graph auditor: jaxpr-level static analysis of captured and
AOT-served programs (``python -m paddle_tpu.tools.audit``).

tpu-lint reads source; this reads the lowered program.  See
:mod:`.core` for the finding/baseline machinery, :mod:`.rules` for the
AUD001+ catalog, :mod:`.runtime` for the capture/serving hooks.
"""
from .core import AuditProgram, Finding, run_rules, walk_jaxprs
from .rules import RULES, default_rules, rule_catalog
from .runtime import (audit_enabled, audit_program, enable, findings,
                      reset, snapshot)

__all__ = ["AuditProgram", "Finding", "RULES", "audit_enabled",
           "audit_program", "default_rules", "enable", "findings",
           "reset", "rule_catalog", "run_rules", "snapshot",
           "walk_jaxprs"]
