"""Graph-audit CLI.

``python -m paddle_tpu.tools.audit`` — builds the in-tree reference
programs (the bench GPT-class captured train step and a tiny served
engine's AOT program ladder), audits them through the same hooks
production capture/serving use, and gates the findings against the
committed ``tools/audit/baseline.txt``.  Exit codes mirror tpu-lint:
0 clean against the baseline, 1 new findings (or a broken build),
2 usage error.

The default run is the tier-1 self-clean gate
(``tests/test_graph_audit.py``): every in-tree step function must
audit clean, and the five rule classes are proven live on synthetic
violating programs by the test fixtures instead.
"""
from __future__ import annotations

import argparse
import sys
import tempfile

from .baseline import (default_baseline_path, diff_against_baseline,
                       load_baseline, write_baseline)
from .rules import default_rules, rule_catalog
from . import runtime


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graph-audit",
        description="jaxpr-level static auditor over the framework's "
                    "captured-step and AOT-served programs (the IR "
                    "sibling of tpu-lint).")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file (default: the committed "
                        "tools/audit/baseline.txt)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="regenerate the baseline from the current "
                        "programs and exit 0")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma-separated rule ids to run "
                        "(e.g. AUD002,AUD003)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--skip-capture", action="store_true",
                   help="skip the captured GPT train-step target")
    p.add_argument("--skip-serve", action="store_true",
                   help="skip the serving-engine target")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress per-finding output; summary only")
    return p


def _build_captured_gpt() -> None:
    """The bench GPT captured step (gpt_tiny class, same model family
    bench.py trains): capturing it with the auditor enabled routes the
    program through the production capture hook."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.incubate.models import (GPTForCausalLM,
                                            GPTPretrainingCriterion,
                                            gpt_tiny)

    pt.seed(0)
    cfg = gpt_tiny(tensor_parallel=False, use_recompute=False)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters())

    @pt.jit.capture_step
    def gpt_step(ids, labels):
        loss = crit(model(ids), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.RandomState(0)
    batch, seq = 2, 32
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size,
                                   (batch, seq)).astype(np.int64))
    labels = pt.to_tensor(rng.randint(0, cfg.vocab_size,
                                      (batch, seq)).astype(np.int64))
    gpt_step(ids, labels)          # first replay: compile + audit hook


def _build_served_engine() -> None:
    """A tiny served-model dir loaded through ``load_engine`` — the
    production load path, so every AOT bucket program passes through
    the serving audit hook."""
    from paddle_tpu.serving import (ModelSpec, ServeConfig, init_params,
                                    load_engine, save_served_model)

    spec = ModelSpec(vocab_size=64, hidden=32, layers=2, heads=2,
                     max_seq_len=64)
    cfg = ServeConfig(decode_buckets=(4,), prefill_buckets=(16,),
                      kv_pages=32, page_size=4, max_inflight=16,
                      max_new_tokens=8)
    with tempfile.TemporaryDirectory(prefix="pt_audit_serve_") as root:
        save_served_model(root, spec, init_params(spec, seed=0),
                          config=cfg)
        engine = load_engine(root)
        engine.close()


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for rid, name, rationale in rule_catalog():
            print(f"{rid}  {name}")
            print(f"       {rationale}")
        return 0

    select = ([r.strip().upper() for r in args.select.split(",")
               if r.strip()] if args.select else None)
    if select and not all(s in {r[0] for r in rule_catalog()}
                          for s in select):
        print(f"graph-audit: unknown rule in --select: {args.select}",
              file=sys.stderr)
        return 2
    if select is not None:
        # narrow the hook-side rule set for this process too
        import os
        keep = set(select)
        disabled = [rid for rid, _, _ in rule_catalog()
                    if rid not in keep]
        os.environ["PT_AUDIT_DISABLE"] = ",".join(disabled)

    runtime.reset()
    runtime.enable()
    errors = []
    try:
        if not args.skip_capture:
            try:
                _build_captured_gpt()
            except Exception as e:
                errors.append(f"captured GPT step build failed: "
                              f"{type(e).__name__}: {e}")
        if not args.skip_serve:
            try:
                _build_served_engine()
            except Exception as e:
                errors.append(f"serving engine build failed: "
                              f"{type(e).__name__}: {e}")
        found = runtime.findings()
    finally:
        runtime.reset()

    for msg in errors:
        print(f"graph-audit: ERROR {msg}", file=sys.stderr)

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        n = write_baseline(baseline_path, found)
        print(f"graph-audit: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} to {baseline_path}")
        return 0

    if args.no_baseline:
        new, old, stale = found, [], []
    else:
        new, old, stale = diff_against_baseline(
            found, load_baseline(baseline_path))

    if not args.quiet:
        for f in new:
            print(f.render())
        for k in stale:
            print(f"stale baseline entry (finding no longer present — "
                  f"prune it): {k}", file=sys.stderr)

    summary = (f"graph-audit: {len(new)} new finding"
               f"{'' if len(new) == 1 else 's'}")
    if old:
        summary += f", {len(old)} baselined"
    if stale:
        summary += f", {len(stale)} stale baseline entries"
    if errors:
        summary += f", {len(errors)} build errors"
    print(summary)
    return 1 if (new or errors) else 0


if __name__ == "__main__":
    sys.exit(main())
