"""Graph auditor core: findings over jaxpr-level programs.

tpu-lint (``tools/lint``) reads Python source; this package reads the
*lowered program* — the jaxprs the framework already produces for its
captured training steps (``jit/capture``) and AOT-served program
families (``serving/engine``).  The hazards it hunts (implicit
reshards, AMP precision leaks, undonated state buffers, request-path
host transfers, missed fusion clusters) are invisible at the AST layer
because the compiler, not the source, decides them.

The machinery deliberately mirrors tpu-lint's conventions so one
mental model covers both gates:

 - a rule is a class with an ``AUD0xx`` id registered in ``RULES``
   (:mod:`.rules`);
 - a finding's :attr:`Finding.key` is content-addressed
   (``program::RULE::<provenance>``) and carries no eqn indices, so
   unrelated model edits never invalidate the committed baseline;
 - the baseline file is a multiset of keys diffed exactly like
   ``tools/lint/baseline.py`` does (that module is reused directly);
 - rules are suppressed per-run with ``--select`` / the lazily read
   ``PT_AUDIT_DISABLE`` env knob (the IR has no place to hang a
   ``# tpu-lint: disable=`` comment, so suppression is rule-level).

Nothing in this module executes the audited program: analysis is a
walk over equations of an already-traced jaxpr.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from jax import core as jcore

__all__ = ["Finding", "AuditProgram", "walk_jaxprs", "GraphView",
           "audit_disabled_rules", "run_rules", "sort_findings"]

_SEVERITIES = ("error", "warning")


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Finding:
    """One audit hit: program + rule + content-addressed provenance.

    ``provenance`` is a short, deterministic description of the
    offending site built from primitive names / avals / specs — never
    from eqn indices — so the baseline key survives unrelated edits to
    the model, exactly like tpu-lint's line-number-free keys.
    ``nbytes`` carries the byte weight where the rule has one (the
    donation audit), 0 otherwise.
    """

    rule: str
    severity: str
    program: str
    provenance: str
    message: str
    nbytes: int = 0

    @property
    def key(self) -> str:
        return f"{self.program}::{self.rule}::{self.provenance}"

    def render(self) -> str:
        mib = f" [{self.nbytes / 2**20:.1f} MiB]" if self.nbytes else ""
        return (f"{self.program}: {self.rule} [{self.severity}]"
                f"{mib} {self.message}")


def sort_findings(findings: Sequence[Finding]) -> List[Finding]:
    """Deterministic report order: program, rule, provenance."""
    return sorted(findings, key=lambda f: (f.program, f.rule,
                                           f.provenance, f.message))


# ---------------------------------------------------------------------------
# audited program
# ---------------------------------------------------------------------------
class AuditProgram:
    """One program under audit: a ClosedJaxpr plus the framework-side
    facts the rules need but the IR alone cannot supply.

    ``donated`` is the set of flat invar indices the caller donates
    (``jit(..., donate_argnums=...)`` resolved to leaf positions);
    ``arg_names`` optionally names those flat invars (pytree key paths)
    for readable donation findings; ``fusion_expected`` +
    ``fusion_rewrites`` let the missed-fusion rule compare what the
    fusion pass *should* have claimed against what it actually
    rewrote; ``memory`` is the PR-14 ``memory_analysis`` block
    (per-kind bytes) harvested beside the program, used to weight
    donation findings against the real argument footprint.
    """

    __slots__ = ("name", "jaxpr", "kind", "donated", "arg_names",
                 "fusion_expected", "fusion_rewrites", "memory")

    def __init__(self, name: str, jaxpr: Any, kind: str = "generic",
                 donated: Sequence[int] = (),
                 arg_names: Optional[Sequence[str]] = None,
                 fusion_expected: bool = False,
                 fusion_rewrites: Optional[Dict[str, int]] = None,
                 memory: Optional[Dict[str, Any]] = None):
        if kind not in ("capture", "serve", "generic"):
            raise ValueError(f"unknown program kind: {kind!r}")
        self.name = name
        self.jaxpr = jaxpr          # jax.core.ClosedJaxpr
        self.kind = kind
        self.donated = frozenset(int(i) for i in donated)
        self.arg_names = list(arg_names) if arg_names is not None else None
        self.fusion_expected = bool(fusion_expected)
        self.fusion_rewrites = dict(fusion_rewrites or {})
        self.memory = dict(memory) if memory else None

    def arg_name(self, i: int) -> str:
        if self.arg_names is not None and 0 <= i < len(self.arg_names):
            return self.arg_names[i]
        return f"arg{i}"


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------
def _inner_jaxprs(params: Dict[str, Any]) -> Iterator[Tuple[str, Any]]:
    """Yield (param_name, jaxpr) for every sub-jaxpr in eqn params —
    pjit bodies, remat bodies, scan/while/cond branches."""
    for k, v in params.items():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            inner = getattr(item, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield k, inner          # ClosedJaxpr -> Jaxpr
            elif hasattr(item, "eqns"):
                yield k, item           # bare Jaxpr


def walk_jaxprs(closed, max_depth: int = 8):
    """Yield ``(jaxpr, path)`` for the top-level jaxpr and every nested
    sub-jaxpr (remat/pjit/scan/cond bodies), depth-first.  ``path`` is
    a ``/``-joined trail of the owning primitives, "" for the top level
    — provenance context only, never part of a baseline key."""
    top = getattr(closed, "jaxpr", closed)

    def _walk(jaxpr, path, depth):
        yield jaxpr, path
        if depth >= max_depth:
            return
        for eqn in jaxpr.eqns:
            for _, inner in _inner_jaxprs(eqn.params):
                sub = f"{path}/{eqn.primitive.name}" if path \
                    else eqn.primitive.name
                yield from _walk(inner, sub, depth + 1)

    yield from _walk(top, "", 0)


class GraphView:
    """Producer/consumer index over one jaxpr level (the audit-side
    sibling of ``fusion_pass._Graph``, without the match helpers)."""

    OUT = -1

    def __init__(self, jaxpr):
        self.jaxpr = jaxpr
        self.eqns = jaxpr.eqns
        self.producer_idx: Dict[Any, int] = {}
        self.consumers: Dict[Any, List[int]] = {}
        for i, eqn in enumerate(self.eqns):
            for ov in eqn.outvars:
                self.producer_idx[ov] = i
            for iv in eqn.invars:
                if not _is_literal(iv):
                    self.consumers.setdefault(iv, []).append(i)
        for ov in jaxpr.outvars:
            if not _is_literal(ov):
                self.consumers.setdefault(ov, []).append(self.OUT)

    def producer(self, v) -> Optional[int]:
        if _is_literal(v):
            return None
        return self.producer_idx.get(v)

    def sole_consumer(self, v) -> Optional[int]:
        cons = self.consumers.get(v, [])
        if len(cons) != 1 or cons[0] == self.OUT:
            return None
        return cons[0]


def _is_literal(v) -> bool:
    return isinstance(v, jcore.Literal)


# ---------------------------------------------------------------------------
# rule selection
# ---------------------------------------------------------------------------
def audit_disabled_rules() -> set:
    """Rule ids disabled via ``PT_AUDIT_DISABLE`` (comma-separated),
    read lazily per run — the PR-3 lazy-knob contract."""
    raw = os.environ.get("PT_AUDIT_DISABLE", "")
    return {t.strip().upper() for t in raw.split(",") if t.strip()}


def run_rules(programs: Sequence[AuditProgram], rules) -> List[Finding]:
    """Apply every rule to every program; deterministic output order.
    A rule that raises poisons neither the run nor its siblings — the
    auditor must never take down a capture or an engine build — but the
    breakage is surfaced as a finding against the rule itself rather
    than swallowed."""
    findings: List[Finding] = []
    for prog in programs:
        for rule in rules:
            try:
                findings.extend(rule.check(prog))
            except Exception as e:  # analysis bug, not a program bug
                findings.append(Finding(
                    rule=rule.id, severity="warning", program=prog.name,
                    provenance="rule-error",
                    message=f"rule crashed: {type(e).__name__}: {e}"))
    return sort_findings(findings)
