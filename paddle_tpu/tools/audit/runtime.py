"""Audit runtime: the capture/serving hook layer.

The auditor is OFF by default and costs one dict lookup per captured
signature when off.  Enabled (``PT_AUDIT=1`` read lazily, or
:func:`enable` programmatically — bench does the latter), it runs at
the two points where the framework already pays a compile:

 - ``jit/capture`` first replay: the captured step's *pre-fusion*
   jaxpr is re-traced and audited once per signature, right after the
   FLOPs/memory harvests that share the same compile-time window.  The
   replay hot path never pays anything — the 1-compile contract the
   bench capture block pins is untouched.
 - ``serving/engine`` AOT build: every bucket executable's traced
   jaxpr is audited while the ladder compiles (load-time only).

Every finding books ``pt_audit_findings_total{rule,severity}`` and is
kept in a process-wide ledger that :func:`snapshot` renders as the
``audit`` block on bench records.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional, Sequence

from .core import AuditProgram, Finding, run_rules
from .rules import default_rules

__all__ = ["audit_enabled", "enable", "reset", "audit_program",
           "audit_captured_step", "audit_serve_trace", "findings",
           "snapshot"]

logger = logging.getLogger("paddle_tpu.audit")

_FALSY = {"0", "false", "no", "off", ""}

_lock = threading.Lock()
_override: Optional[bool] = None
_findings: List[Finding] = []
_programs: List[str] = []
_metric = None
_metric_failed = False


def audit_enabled() -> bool:
    """Lazy PT_AUDIT knob (default off), overridable via :func:`enable`
    — the PR-3 lazy-env contract."""
    if _override is not None:
        return _override
    return os.environ.get("PT_AUDIT", "0").strip().lower() not in _FALSY


def enable(on: bool = True) -> None:
    global _override
    _override = bool(on)


def reset() -> None:
    """Clear the ledger and any programmatic enable (tests/bench)."""
    global _override
    with _lock:
        _override = None
        _findings.clear()
        _programs.clear()


def findings() -> List[Finding]:
    with _lock:
        return list(_findings)


def snapshot() -> Dict[str, Any]:
    """The ``audit`` block bench records carry: counts by rule and
    severity plus the audited program names — never the full messages
    (records stay one JSON line)."""
    with _lock:
        by_rule: Dict[str, int] = {}
        by_sev: Dict[str, int] = {}
        for f in _findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
            by_sev[f.severity] = by_sev.get(f.severity, 0) + 1
        return {
            "enabled": audit_enabled(),
            "programs": list(_programs),
            "findings": len(_findings),
            "by_rule": by_rule,
            "by_severity": by_sev,
        }


def _book(new: Sequence[Finding]) -> None:
    global _metric, _metric_failed
    if not new:
        return
    try:
        if _metric is None and not _metric_failed:
            from ...observability.metrics import get_registry
            _metric = get_registry().counter(
                "pt_audit_findings_total",
                "graph-audit findings booked at capture/serve compile "
                "time", ("rule", "severity"))
    except Exception:  # metrics are optional plumbing
        _metric_failed = True
    if _metric is not None:
        try:
            for f in new:
                _metric.inc(rule=f.rule, severity=f.severity)
        except Exception:
            pass


def audit_program(prog: AuditProgram) -> List[Finding]:
    """Run the default rule set over one program, book and ledger the
    findings.  Never raises — the auditor must not take down a capture
    or an engine build."""
    try:
        found = run_rules([prog], default_rules())
    except Exception:
        logger.debug("audit failed for %s", prog.name, exc_info=True)
        return []
    with _lock:
        _programs.append(prog.name)
        _findings.extend(found)
    _book(found)
    for f in found:
        logger.info("audit: %s", f.render())
    return found


# ---------------------------------------------------------------------------
# framework entry points
# ---------------------------------------------------------------------------
_ARG_LABELS_CAPTURE = ("params", "buffers", "opt_states", "rng_ctr",
                       "lrs", "traced")


def _flat_arg_names(args, labels) -> List[str]:
    """Flat invar names from pytree key paths: ``params['w']`` etc. —
    deterministic (dict insertion order), so donation provenance keys
    are stable across runs."""
    import jax
    names = []
    flat, _ = jax.tree_util.tree_flatten_with_path(tuple(args))
    for path, _leaf in flat:
        label = labels[path[0].idx] if path else "arg"
        names.append(label + jax.tree_util.keystr(path[1:]))
    return names


def audit_captured_step(entry, params, buffers, opt_states, rng_ctr,
                        lrs, traced) -> List[Finding]:
    """Audit one captured step at compile time: re-trace the PRE-fusion
    pure function (what ``fusion_pass.wrap`` itself matched, so the
    missed-fusion cross-check compares like with like) and run the
    rules.  One extra trace, zero compiles, zero steady-state cost."""
    import jax
    from ...ops import fusion_pass
    pure = getattr(entry, "pure", None)
    if pure is None:
        return []
    try:
        args = (params, buffers, opt_states, rng_ctr, lrs, traced)
        closed = jax.make_jaxpr(pure)(*args)
        n_donated = len(jax.tree_util.tree_leaves(
            (params, buffers, opt_states)))
        prog = AuditProgram(
            name=entry.name, jaxpr=closed, kind="capture",
            donated=range(n_donated),
            arg_names=_flat_arg_names(args, _ARG_LABELS_CAPTURE),
            fusion_expected=fusion_pass.fusion_enabled(),
            fusion_rewrites=entry.fusion,
            memory=entry.memory)
    except Exception:
        logger.debug("captured-step audit trace failed for %s",
                     getattr(entry, "name", "?"), exc_info=True)
        return []
    return audit_program(prog)


_ARG_LABELS_SERVE = ("params", "k_flat", "v_flat", "tokens",
                     "positions", "page_tables")


def audit_serve_trace(name: str, closed, n_params: int,
                      n_kv: int, args=None, labels=None) -> List[Finding]:
    """Audit one AOT serve program from its traced jaxpr.  Donation
    layout mirrors the engine's donate_argnums: the ``n_kv`` KV pool
    leaves (value pools, plus scale pools on a quantized ladder) right
    after the ``n_params`` weight leaves.  ``labels`` overrides the
    positional arg names when the engine's argument layout differs
    from the fp32 default (the int8 ladder inserts k_scale/v_scale)."""
    names = None
    if args is not None:
        try:
            names = _flat_arg_names(args, labels or _ARG_LABELS_SERVE)
        except Exception:
            names = None
    prog = AuditProgram(
        name=name, jaxpr=closed, kind="serve",
        donated=range(n_params, n_params + n_kv),
        arg_names=names, fusion_expected=False)
    return audit_program(prog)
