"""Audit baseline: the same grandfather-then-gate contract as
``tools/lint/baseline.py`` — that module's loader and multiset differ
are reused verbatim, only the header and default path differ.  Keys
are ``program::RULE::<provenance>``: content-addressed and free of eqn
indices, so unrelated model edits never invalidate the file.
"""
from __future__ import annotations

import os

from ..lint.baseline import diff_against_baseline, load_baseline

__all__ = ["default_baseline_path", "load_baseline", "write_baseline",
           "diff_against_baseline"]

_HEADER = """\
# graph-audit baseline — grandfathered findings.
#
# Every entry is `program::RULE::<provenance>`.  The gate fails only
# on findings NOT in this file.  Regenerate after intentional changes
# with:
#     python -m paddle_tpu.tools.audit --write-baseline
# Shrink it over time; never grow it to dodge a fix.
"""


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


def write_baseline(path: str, findings) -> int:
    keys = sorted(f.key for f in findings)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(_HEADER)
        for k in keys:
            fh.write(k + "\n")
    return len(keys)
