"""tpu-lint rule catalog.

Every rule targets a concrete way Python code silently destroys TPU
throughput (or correctness) in a JAX-backed stack.  The catalog is the
distillation of the failure modes this repo has actually hit or guards
against — retrace storms, host round-trips in step loops, tracer leaks —
plus the classic ones the JAX docs warn about.

Rules are small classes with event hooks (``on_call``, ``on_if``,
``on_assign``, ``on_except``, ``on_while``, ``on_for``, ``on_with``);
the :class:`~.core.Linter` owns all traversal and scope state.  Register
new rules with :func:`register`.
"""
from __future__ import annotations

import ast
import re

from .core import dotted

__all__ = ["Rule", "register", "default_rules", "RULES", "rule_catalog"]

RULES: dict[str, type] = {}


def register(cls):
    """Class decorator adding a rule to the default registry."""
    RULES[cls.id] = cls
    return cls


class Rule:
    id = "TPU000"
    name = "abstract"
    rationale = ""


def default_rules(select=None):
    """Instantiate the registry (optionally only ``select`` rule ids)."""
    ids = sorted(RULES) if select is None else list(select)
    out = []
    for rid in ids:
        if rid not in RULES:
            raise KeyError(f"unknown rule id {rid!r} "
                           f"(known: {', '.join(sorted(RULES))})")
        out.append(RULES[rid]())
    return out


def rule_catalog():
    return [(rid, RULES[rid].name, RULES[rid].rationale)
            for rid in sorted(RULES)]


# -- shared predicates ------------------------------------------------------

_JIT_CONSTRUCTORS = {"jax.jit", "jit", "pjit", "jax.pjit",
                     "jax.experimental.pjit.pjit"}

# attribute reads on a tensor that are static under tracing (shape
# metadata is concrete even on tracers)
_SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "name"}
# calls whose result is host-static even when an arg is traced
_SAFE_CALLS = {"isinstance", "len", "hasattr", "getattr", "callable",
               "type", "id"}


def _is_jit_call(node: ast.Call) -> bool:
    name = dotted(node.func)
    if name in ("functools.partial", "partial") and node.args:
        name = dotted(node.args[0])
    return name in _JIT_CONSTRUCTORS


def _literal(node: ast.AST) -> bool:
    return isinstance(node, (ast.Constant, ast.List, ast.Tuple, ast.Dict,
                             ast.Set))


def _receiver_already_synced(recv: ast.AST, methods) -> bool:
    """True when the receiver expression is itself a host-sync call
    (``x.numpy().tolist()``) — the inner call carries the report."""
    return (isinstance(recv, ast.Call)
            and isinstance(recv.func, ast.Attribute)
            and recv.func.attr in methods)


def _hazard_params(expr: ast.AST, params: set) -> list:
    """Parameter references in ``expr`` whose *value* feeds truthiness.

    Skips statically-safe constructs: ``x is None``, ``isinstance(x, T)``,
    ``len(x)``, and metadata reads like ``x.shape[0] > 1``.
    """
    hits = []

    def walk(n, parent_attr=None):
        if isinstance(n, ast.Attribute):
            if n.attr in _SAFE_ATTRS:
                return  # x.shape / x.ndim / x.dtype — static
            walk(n.value)
            return
        if isinstance(n, ast.Call):
            if dotted(n.func) in _SAFE_CALLS:
                return
            for a in n.args:
                walk(a)
            for k in n.keywords:
                walk(k.value)
            walk(n.func)
            return
        if isinstance(n, ast.Compare):
            ops_safe = all(isinstance(o, (ast.Is, ast.IsNot, ast.In,
                                          ast.NotIn)) for o in n.ops)
            if ops_safe:
                return  # `x is None`, `k in d` — identity/containment
            walk(n.left)
            for c in n.comparators:
                walk(c)
            return
        if isinstance(n, ast.Name):
            if n.id in params:
                hits.append(n)
            return
        for c in ast.iter_child_nodes(n):
            walk(c)

    walk(expr)
    return hits


# -- the catalog ------------------------------------------------------------

@register
class JitInLoop(Rule):
    id = "TPU001"
    name = "jit-construction-in-hot-path"
    rationale = ("jax.jit/pjit called inside a loop or per forward call "
                 "builds a fresh cache entry every iteration — a retrace "
                 "storm that recompiles instead of reusing the program")

    def on_call(self, node, ctx):
        if not _is_jit_call(node):
            return
        # a decorator list is visited as part of the funcdef; a
        # decorator on a nested def inside a loop still retraces, so no
        # special-casing needed — position decides.
        if ctx.in_loop:
            ctx.report(node, self.id,
                       "jax.jit constructed inside a loop; hoist it out "
                       "so the compiled program is reused")
        elif ctx.in_forward():
            ctx.report(node, self.id,
                       "jax.jit constructed per call inside "
                       "forward/__call__; build once (e.g. in __init__) "
                       "and reuse")


@register
class TracedBool(Rule):
    id = "TPU002"
    name = "python-branch-on-traced-value"
    rationale = ("`if`/`while` on a traced tensor raises "
                 "TracerBoolConversionError under jit (or silently bakes "
                 "one branch in); use lax.cond/jnp.where/lax.while_loop")

    def _check(self, test, node, ctx, kind):
        fi = ctx.innermost_traced()
        if fi is None:
            return
        for ref in _hazard_params(test, fi.params):
            ctx.report(node, self.id,
                       f"python `{kind}` on traced value {ref.id!r} "
                       f"inside trace target {fi.name!r}; use lax.cond / "
                       f"jnp.where / lax.while_loop")
            return  # one report per statement is enough

    def on_if(self, node, ctx):
        self._check(node.test, node, ctx, "if")

    def on_while(self, node, ctx):
        self._check(node.test, node, ctx, "while")


@register
class HostSyncInForward(Rule):
    id = "TPU003"
    name = "host-sync-in-forward-or-kernel"
    rationale = ("`.item()`/`.numpy()`/np.asarray/float(tensor) in a "
                 "forward or op body blocks on device->host transfer every "
                 "call, serializing the pipeline (and crashes under jit)")

    _SYNC_METHODS = {"item", "numpy", "tolist", "__array__"}
    _NP_FUNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                 "jax.device_get", "device_get"}

    def _applicable(self, ctx):
        return (ctx.in_forward() or ctx.innermost_traced() is not None
                or (ctx.kernel_path and ctx.func_stack))

    def on_call(self, node, ctx):
        if not self._applicable(ctx):
            return
        name = dotted(node.func)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in self._SYNC_METHODS):
            if _receiver_already_synced(node.func.value,
                                        self._SYNC_METHODS):
                return  # x.numpy().tolist(): one sync, one report
            ctx.report(node, self.id,
                       f".{node.func.attr}() forces a device->host sync "
                       f"in a hot path; keep the value on device "
                       f"(jnp ops accept 0-d arrays)")
            return
        if name in self._NP_FUNCS:
            if node.args and _literal(node.args[0]):
                return  # np.asarray([0, 1]) — host constant, no transfer
            ctx.report(node, self.id,
                       f"{name}() on a device value forces a host "
                       f"round-trip in a hot path; use jnp.asarray or "
                       f"keep the array on device")
            return
        # float(x)/int(x)/bool(x) directly on a forward/traced parameter
        if (name in ("float", "int", "bool") and node.args
                and isinstance(node.args[0], ast.Name)):
            fi = ctx.innermost_traced()
            owners = [f for f in ctx.func_stack
                      if f.is_forward or f is fi]
            if any(node.args[0].id in f.params for f in owners):
                ctx.report(node, self.id,
                           f"{name}() on tensor argument "
                           f"{node.args[0].id!r} synchronizes with the "
                           f"host (TracerConversion under jit)")


@register
class TracerLeak(Rule):
    id = "TPU004"
    name = "tracer-leak-via-side-effect"
    rationale = ("assigning to self.*/globals inside a jitted or traced "
                 "function leaks tracers out of the trace — a "
                 "UnexpectedTracerError later, or stale constants baked in")

    def on_assign(self, node, ctx):
        fi = ctx.innermost_traced()
        if fi is None:
            return
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            for sub in ast.walk(t):
                if (isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"):
                    ctx.report(node, self.id,
                               f"assignment to self.{sub.attr} inside "
                               f"trace target {fi.name!r} leaks a tracer; "
                               f"return the value instead")
                    return
                if (isinstance(sub, ast.Name)
                        and sub.id in fi.globals_decl):
                    ctx.report(node, self.id,
                               f"assignment to global {sub.id!r} inside "
                               f"trace target {fi.name!r} leaks a tracer")
                    return


@register
class BadStaticArgnums(Rule):
    id = "TPU005"
    name = "invalid-static-argnums"
    rationale = ("static_argnums must be hashable ints (and argnames "
                 "strings); strings/floats/tensors there either raise or "
                 "mark a tensor static, retracing on every distinct value")

    def on_call(self, node, ctx):
        if not _is_jit_call(node):
            return
        for kw in node.keywords:
            if kw.arg == "static_argnums":
                self._check_elems(
                    kw.value, node, ctx, want=int,
                    hint="index positions are ints; for names use "
                         "static_argnames")
            elif kw.arg == "static_argnames":
                self._check_elems(
                    kw.value, node, ctx, want=str,
                    hint="argument names are strings; for positions use "
                         "static_argnums")

    @staticmethod
    def _elems(value):
        if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
            return value.elts
        return [value]

    def _check_elems(self, value, node, ctx, want, hint):
        for el in self._elems(value):
            if isinstance(el, ast.Constant):
                ok = isinstance(el.value, want) and not (
                    want is int and isinstance(el.value, bool))
                if not ok:
                    ctx.report(node, self.id,
                               f"non-{want.__name__} constant "
                               f"{el.value!r} in static_arg spec: {hint}")
            elif _literal(el):
                ctx.report(node, self.id,
                           f"unhashable literal in static_arg spec: "
                           f"{hint}")


@register
class ScanBodyMutation(Rule):
    id = "TPU006"
    name = "captured-mutation-in-scan-body"
    rationale = ("mutating a captured list/dict inside a lax.scan/"
                 "while_loop body runs once at trace time, not per step — "
                 "the mutation silently records only tracer garbage")

    _MUTATORS = {"append", "extend", "insert", "update", "pop", "popitem",
                 "setdefault", "remove", "clear", "add", "discard"}

    def _captured(self, name, ctx):
        fi = ctx.current_func
        return (fi is not None and fi.is_scan_body
                and name not in fi.local_stores)

    def on_call(self, node, ctx):
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in self._MUTATORS
                and isinstance(f.value, ast.Name)
                and self._captured(f.value.id, ctx)):
            ctx.report(node, self.id,
                       f"{f.value.id}.{f.attr}() mutates a captured "
                       f"container inside a scan/while_loop body; carry "
                       f"it through the loop state instead")

    def on_assign(self, node, ctx):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if (isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and self._captured(t.value.id, ctx)):
                ctx.report(node, self.id,
                           f"subscript-assignment to captured "
                           f"{t.value.id!r} inside a scan/while_loop "
                           f"body; carry it through the loop state")


@register
class TransferInTrainLoop(Rule):
    id = "TPU007"
    name = "device-transfer-in-train-loop"
    rationale = ("jax.device_get/.numpy()/.item() every training step "
                 "stalls the device pipeline; sync once per logging "
                 "interval, or after the loop")

    _LOOP_FUNC = re.compile(r"(train|fit|epoch|run_steps?|step_loop)",
                            re.IGNORECASE)
    _SYNC_METHODS = {"numpy", "item", "tolist"}
    _SYNC_FUNCS = {"jax.device_get", "device_get", "np.asarray",
                   "numpy.asarray", "np.array", "numpy.array"}

    def on_call(self, node, ctx):
        if not ctx.in_loop:
            return
        if not any(self._LOOP_FUNC.search(fi.name)
                   for fi in ctx.func_stack):
            return
        name = dotted(node.func)
        hit = None
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in self._SYNC_METHODS):
            if _receiver_already_synced(node.func.value,
                                        self._SYNC_METHODS):
                return
            hit = f".{node.func.attr}()"
        elif name in self._SYNC_FUNCS:
            if node.args and _literal(node.args[0]):
                return
            hit = f"{name}()"
        if hit:
            ctx.report(node, self.id,
                       f"{hit} inside a training-step loop forces a "
                       f"device sync every iteration; hoist it out or "
                       f"sync on a logging interval")


@register
class SwallowedDistributedError(Rule):
    id = "TPU008"
    name = "swallowed-error-in-distributed-path"
    rationale = ("a bare/blanket except around collective or rendezvous "
                 "code turns one dead rank into a silent hang of every "
                 "other rank at the next barrier")

    _BLANKET = {"Exception", "BaseException"}

    def on_except(self, node, ctx):
        if not ctx.distributed_path:
            return
        if node.type is None:
            ctx.report(node, self.id,
                       "bare `except:` in distributed code swallows "
                       "everything incl. KeyboardInterrupt; catch the "
                       "specific failure and at least log it")
            return
        names = {dotted(t) for t in (
            node.type.elts if isinstance(node.type, ast.Tuple)
            else [node.type])}
        if names & self._BLANKET and self._trivial_body(node.body):
            ctx.report(node, self.id,
                       "`except Exception: pass` in distributed code "
                       "hides rank failures (peers hang at the next "
                       "collective); log the error or narrow the type")

    @staticmethod
    def _trivial_body(body):
        for stmt in body:
            if isinstance(stmt, ast.Pass):
                continue
            if isinstance(stmt, ast.Continue):
                continue
            if (isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Constant)):
                continue  # `...` or a lone docstring
            return False
        return True


@register
class RawSleepPollLoop(Rule):
    id = "TPU009"
    name = "raw-sleep-poll-loop"
    rationale = ("a bare time.sleep in a poll/retry loop in coordination "
                 "code wakes a whole restarted fleet in lockstep and "
                 "hammers the store; use utils.retry (retry_call / "
                 "wait_until) for jittered backoff with a deadline")

    _SLEEP_NAMES = {"time.sleep", "sleep", "_time.sleep"}

    def on_call(self, node, ctx):
        if not (ctx.distributed_path or ctx.core_path):
            return
        if not ctx.in_loop:
            return
        if dotted(node.func) in self._SLEEP_NAMES:
            ctx.report(node, self.id,
                       "raw sleep() in a poll/retry loop; use "
                       "utils.retry.retry_call/wait_until (jittered "
                       "backoff, deadline) or suppress if a fixed "
                       "cadence is genuinely wanted")


@register
class BarePrintInLibrary(Rule):
    id = "TPU010"
    name = "bare-print-in-library"
    rationale = ("print() in library code writes to stdout unconditionally"
                 " — it can't be filtered, rate-limited, or collected per"
                 " process, and it corrupts machine-read stdout (bench JSON"
                 " lines, launch protocols); route messages through"
                 " paddle_tpu.observability (get_logger / the event sink)."
                 " CLI entry points, tools/ and tests are exempt, as is"
                 " print(..., file=...) which targets a stream on purpose")

    def on_call(self, node, ctx):
        if not ctx.library_path:
            return
        if dotted(node.func) != "print":
            return
        if any(kw.arg == "file" for kw in node.keywords):
            return  # explicit stream choice (stderr protocols etc.)
        ctx.report(node, self.id,
                   "bare print() in paddle_tpu library code; use "
                   "observability.get_logger(__name__) (or emit a "
                   "structured event), or pass an explicit file=")


def _donate_spec(call: ast.Call):
    """Donated positions of a jit construction, or None if it donates
    nothing.  ``"all"`` when the spec is present but not a literal int
    tuple (donate_argnames, computed specs) — every positional arg is
    then treated as consumed."""
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                and not isinstance(v.value, bool):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
            out = set()
            for el in v.elts:
                if (isinstance(el, ast.Constant)
                        and isinstance(el.value, int)
                        and not isinstance(el.value, bool)):
                    out.add(el.value)
                else:
                    return "all"
            return out
        return "all"
    return None


@register
class DonatedBufferReuse(Rule):
    id = "TPU011"
    name = "donated-buffer-reuse"
    rationale = ("an argument passed at a donate_argnums position is "
                 "invalidated by the call — XLA aliases its buffer into "
                 "the output — so reading it afterwards raises 'Array "
                 "has been deleted' (or reads reused memory on backends "
                 "that alias eagerly); rebind the name to the call's "
                 "output instead")

    # flow-sensitive, so the analysis is a private in-order scan of each
    # function body rather than the shared on_call/on_assign events
    # (which carry no statement-order state)
    def on_funcdef(self, node, ctx):
        st = ({}, {}, set())  # donating, consumed, reported node ids
        for stmt in node.body:
            self._stmt(stmt, st, ctx)

    def _stmt(self, s, st, ctx):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return  # nested scopes get their own on_funcdef pass
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._simple(s.iter, st, ctx)
            self._clear_stores(s.target, st)
            # two passes over a loop body: the second catches
            # loop-carried reuse (f(params) every iteration with no
            # rebind donates an already-deleted buffer on iteration 2)
            for _ in (0, 1):
                for sub in s.body:
                    self._stmt(sub, st, ctx)
            for sub in s.orelse:
                self._stmt(sub, st, ctx)
            return
        if isinstance(s, ast.While):
            self._simple(s.test, st, ctx)
            for _ in (0, 1):
                for sub in s.body:
                    self._stmt(sub, st, ctx)
            for sub in s.orelse:
                self._stmt(sub, st, ctx)
            return
        if isinstance(s, ast.If):
            self._simple(s.test, st, ctx)
            for sub in s.body + s.orelse:
                self._stmt(sub, st, ctx)
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._simple(item, st, ctx)
            for sub in s.body:
                self._stmt(sub, st, ctx)
            return
        if isinstance(s, ast.Try):
            for sub in s.body:
                self._stmt(sub, st, ctx)
            for h in s.handlers:
                for sub in h.body:
                    self._stmt(sub, st, ctx)
            for sub in s.orelse + s.finalbody:
                self._stmt(sub, st, ctx)
            return
        self._simple(s, st, ctx)

    def _simple(self, s, st, ctx):
        donating, consumed, reported = st
        # consuming calls in this statement: a bound donating callable,
        # or a direct jax.jit(fn, donate_argnums=...)(args) invocation
        consuming = []
        for c in ast.walk(s):
            if not isinstance(c, ast.Call):
                continue
            spec = None
            if isinstance(c.func, ast.Call) and _is_jit_call(c.func):
                spec = _donate_spec(c.func)
            elif not _is_jit_call(c):
                name = dotted(c.func)
                if name:
                    spec = donating.get(name)
            if spec is not None:
                consuming.append((c, spec))
        # reads are checked against names consumed BEFORE this
        # statement, so a consuming call's own arguments only fire when
        # an earlier call already donated them
        for n in ast.walk(s):
            if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                    and n.id in consumed and id(n) not in reported):
                reported.add(id(n))
                line, callee = consumed[n.id]
                ctx.report(n, self.id,
                           f"{n.id!r} was donated to {callee}() at line "
                           f"{line} and its buffer is no longer valid; "
                           f"rebind the name to the call's output (or "
                           f"drop donate_argnums for this argument)")
        for c, spec in consuming:
            callee = dotted(c.func) or "a jitted callable"
            for pos, a in enumerate(c.args):
                if isinstance(a, ast.Name) and (spec == "all"
                                                or pos in spec):
                    consumed[a.id] = (c.lineno, callee)
        # stores AFTER consumption: `params = f(params)` rebinds the
        # name to the fresh output, clearing the hazard
        if isinstance(s, ast.Assign):
            v = s.value
            if isinstance(v, ast.Call) and _is_jit_call(v) \
                    and _donate_spec(v) is not None:
                for t in s.targets:
                    tname = dotted(t)
                    if tname:
                        donating[tname] = _donate_spec(v)
            for t in s.targets:
                self._clear_stores(t, st)
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            self._clear_stores(s.target, st)
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                self._clear_stores(t, st)

    @staticmethod
    def _clear_stores(target, st):
        _, consumed, _ = st
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                consumed.pop(n.id, None)


@register
class RawPallasCall(Rule):
    id = "TPU012"
    name = "raw-pallas-call-outside-ops"
    rationale = ("direct pl.pallas_call outside paddle_tpu/ops/ bypasses "
                 "the kernel dispatch layer — the use_pallas_kernels "
                 "flag, the one-time lowering canary with XLA fallback, "
                 "and the autotuner cache all live there; a raw call "
                 "site can't be switched off, falls over instead of "
                 "falling back when Mosaic rejects the kernel, and runs "
                 "with unsearched launch configs. Wrap the kernel in "
                 "paddle_tpu/ops/ and dispatch through nn.functional")

    _PALLAS_CALLS = {"pl.pallas_call", "pallas_call",
                     "pallas.pallas_call",
                     "jax.experimental.pallas.pallas_call"}

    def on_call(self, node, ctx):
        if re.search(r"(^|/)paddle_tpu/ops(/|$)", ctx.path_posix):
            return
        if dotted(node.func) in self._PALLAS_CALLS:
            ctx.report(node, self.id,
                       "raw pallas_call outside paddle_tpu/ops/; move "
                       "the kernel into paddle_tpu/ops/ and route "
                       "callers through the dispatch layer (flag + "
                       "fallback canary + autotuner)")


@register
class HostSyncInSpan(Rule):
    id = "TPU013"
    name = "host-sync-inside-open-trace-span"
    rationale = ("`.item()`/np.asarray/block_until_ready inside an open "
                 "RecordEvent / tracer phase span blocks the host while "
                 "the span clock runs — the span then measures the "
                 "device drain, not the work it names, poisoning phase "
                 "histograms and the overlap fraction; sync after the "
                 "span closes (spans must time dispatch, not transfers)")

    # `with RecordEvent("name"):` in any spelling, and the step
    # tracer's context managers: `with tr.phase("backward"):` /
    # `with tracer.span(...)`
    _SPAN_FUNCS = {"RecordEvent"}
    _SPAN_ATTRS = {"phase", "span"}
    _SYNC_METHODS = {"item", "numpy", "tolist", "__array__",
                     "block_until_ready"}
    _SYNC_FUNCS = {"np.asarray", "np.array", "numpy.asarray",
                   "numpy.array", "jax.device_get", "device_get",
                   "jax.block_until_ready", "block_until_ready"}

    def _opens_span(self, node):
        for item in node.items:
            ce = item.context_expr
            if not isinstance(ce, ast.Call):
                continue
            name = dotted(ce.func)
            if name in self._SPAN_FUNCS \
                    or name.rpartition(".")[2] in self._SPAN_FUNCS:
                return name or "RecordEvent"
            # attribute form survives non-name receivers
            # (get_tracer().phase(...)) that dotted() can't render
            if isinstance(ce.func, ast.Attribute) \
                    and ce.func.attr in self._SPAN_ATTRS:
                return name or f"<tracer>.{ce.func.attr}"
        return None

    def on_with(self, node, ctx):
        span = self._opens_span(node)
        if span is None:
            return
        for call, what in self._sync_calls(node.body):
            ctx.report(call, self.id,
                       f"{what} while the {span} span is open blocks "
                       f"the host inside the timed window; move the "
                       f"sync outside the span")

    def _sync_calls(self, body):
        hits = []

        def walk(n):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return  # deferred execution — not inside the span
            if isinstance(n, ast.Call):
                name = dotted(n.func)
                if (isinstance(n.func, ast.Attribute)
                        and n.func.attr in self._SYNC_METHODS):
                    if not _receiver_already_synced(n.func.value,
                                                    self._SYNC_METHODS):
                        hits.append((n, f".{n.func.attr}()"))
                elif name in self._SYNC_FUNCS:
                    if not (n.args and _literal(n.args[0])):
                        hits.append((n, f"{name}()"))
            for c in ast.iter_child_nodes(n):
                walk(c)

        for stmt in body:
            walk(stmt)
        return hits


@register
class CollectiveInParamLoop(Rule):
    id = "TPU014"
    name = "unfused-collective-in-param-loop"
    rationale = ("a psum/all_reduce per parameter inside a Python loop "
                 "emits hundreds of latency-bound small collectives per "
                 "step — each pays the full ICI round-trip for a few KB; "
                 "flat-concat the group and reduce once per size-targeted "
                 "bucket (distributed/grad_buckets.py), which also gives "
                 "the latency-hiding scheduler one fusible op to overlap")

    # reduction-family collectives (jax.lax + this repo's wrappers);
    # matched on the last dotted component so `lax.psum`, `dist.
    # all_reduce` and bare `psum` all hit
    _COLLECTIVES = {"psum", "pmean", "psum_scatter", "all_reduce",
                    "all_gather", "reduce_scatter"}
    # the loop looks per-parameter: its target/iterable mentions
    # params/grads/weights (model.parameters(), grads.items(), ...)
    _PARAM_ITER = re.compile(
        r"(param|grad|weight|named_parameters|state_dict|\.values\(\))",
        re.IGNORECASE)

    def _per_param(self, node):
        try:
            text = ast.unparse(node.target) + " " + ast.unparse(node.iter)
        except Exception:
            return False
        return bool(self._PARAM_ITER.search(text))

    def on_for(self, node, ctx):
        if not ctx.library_path:
            return
        if not self._per_param(node):
            return
        for call, name in self._collective_calls(node.body):
            ctx.report(call, self.id,
                       f"{name}() per parameter in a Python loop; "
                       f"flat-concat the group and emit ONE reduction "
                       f"per bucket (distributed/grad_buckets.py "
                       f"partition_buckets/apply_bucketed_reduction)")

    def _collective_calls(self, body):
        hits = []

        def walk(n):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return  # deferred execution — not per-iteration work
            if isinstance(n, ast.For) and self._per_param(n):
                return  # the nested loop's own on_for event reports it
            if isinstance(n, ast.Call):
                name = dotted(n.func)
                if name.rpartition(".")[2] in self._COLLECTIVES:
                    hits.append((n, name))
            for c in ast.iter_child_nodes(n):
                walk(c)

        for stmt in body:
            walk(stmt)
        return hits


@register
class AdHocPartitionSpecInModel(Rule):
    id = "TPU015"
    name = "ad-hoc-partitionspec-in-model-code"
    rationale = ("an inline PartitionSpec in model/bench code forks the "
                 "sharding layout from the canonical SpecLayout table "
                 "(distributed/auto_parallel/spec_layout.py) — a mesh-"
                 "axis rename or a layout fix then silently misses the "
                 "call site, and the Megatron pairing rules (column out-"
                 "dim + its bias over tp; row in-dim over tp, bias "
                 "replicated) stop being reviewable in one place; ask "
                 "the layout table for the role instead")

    # model/bench code — where layouts must come from the table. The
    # layout engine, train_step and the parallel-layer library are the
    # table's implementation/plumbing and stay free to build specs.
    _MODEL_PATHS = re.compile(
        r"((^|/)paddle_tpu/(incubate|vision)/models(/|$)"
        r"|(^|/)bench[^/]*\.py$)")
    _SPEC_CALLS = {"PartitionSpec", "P", "PS"}

    def on_call(self, node, ctx):
        if not self._MODEL_PATHS.search(ctx.path_posix):
            return
        name = dotted(node.func)
        if name.rpartition(".")[2] in self._SPEC_CALLS:
            ctx.report(node, self.id,
                       f"inline {name}(...) in model/bench code; take "
                       f"the spec from the canonical layout table "
                       f"(distributed/auto_parallel/spec_layout."
                       f"SpecLayout) so dp/fsdp/tp placements stay in "
                       f"one reviewable place")


@register
class UnfusedResidualNorm(Rule):
    id = "TPU016"
    name = "manually-composed-fusable-sequence"
    rationale = ("a residual add composed inline with a layer norm "
                 "(`ln(x + attn)`) materializes the sum as a separate HBM "
                 "round-trip and hides the pair from call sites that "
                 "bypass the jaxpr fusion pass; layer_norm and "
                 "nn.LayerNorm take residual= (fused_add_layer_norm is "
                 "the named form), which feeds the fused_layer_norm "
                 "kernel's in-kernel add and is also what the graph-level "
                 "fusion pass recognizes as one residual_ln cluster")

    # model-layer code where fusable sequences get hand-written; ops/
    # and the lint tool itself stay free to compose primitives
    _FUSABLE_PATHS = re.compile(
        r"(^|/)paddle_tpu/(nn|incubate/models)(/|$)")
    # a LayerNorm module bound on self/a module object: self.ln1, the
    # embedding's self.layer_norm, post_norm, ...
    _NORM_ATTR = re.compile(r"^((layer_?)?norm\d*|ln\d*)$", re.IGNORECASE)

    def _is_norm_call(self, node):
        name = dotted(node.func)
        last = name.rpartition(".")[2]
        if last == "layer_norm":
            return name or last
        # attribute form only for self-bound layers (self.ln1, self.
        # layer_norm) — jnp.linalg.norm and friends are not layer norms
        if (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and self._NORM_ATTR.match(node.func.attr)):
            return name or node.func.attr
        return None

    @staticmethod
    def _is_add(expr):
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            return True
        return (isinstance(expr, ast.Call)
                and dotted(expr.func).rpartition(".")[2] == "add")

    def on_call(self, node, ctx):
        if not self._FUSABLE_PATHS.search(ctx.path_posix):
            return
        name = self._is_norm_call(node)
        if name is None or not node.args:
            return
        if any(kw.arg == "residual" for kw in node.keywords):
            return  # already on the fused entry point
        if self._is_add(node.args[0]):
            ctx.report(node, self.id,
                       f"residual add composed inline with {name}(); "
                       f"pass the addend as residual= (or call "
                       f"fused_add_layer_norm) so the add+LN pair runs "
                       f"as one fused kernel and the fusion pass sees "
                       f"one residual_ln cluster")


@register
class DeviceArrayAccumulation(Rule):
    id = "TPU018"
    name = "device-array-accumulation-in-step-loop"
    rationale = ("appending per-step device results (losses, logits, "
                 "grads) to a Python container inside a training loop "
                 "pins every step's HBM buffer for the life of the list "
                 "— the run leaks device memory linearly in steps and "
                 "OOMs long after the step itself fits; convert to a "
                 "host scalar first (float(loss) / .item() — one sync "
                 "on the logging cadence) or let telemetry keep the "
                 "bounded history")

    # same scope gate as TPU007: only loops owned by a function whose
    # name says it is a training loop
    _LOOP_FUNC = re.compile(r"(train|fit|epoch|run_steps?|step_loop)",
                            re.IGNORECASE)
    _ACCUM_METHODS = {"append", "extend", "insert"}
    # host conversions that detach the value from device memory — an
    # accumulation wrapped in (or chained through) one of these is the
    # correct idiom, not a leak
    _HOST_CASTS = {"float", "int", "bool", "str", "np.asarray",
                   "np.array", "numpy.asarray", "numpy.array",
                   "jax.device_get", "device_get"}
    _SYNC_METHODS = {"item", "numpy", "tolist", "tobytes", "__array__"}
    # identifier components that name per-step device results; matched
    # as WHOLE dotted components so `step_times` / `lossy` never hit
    _DEVICE_NAMES = re.compile(
        r"^(steps?|train_step|model|net|forward|criterion|loss_fn|"
        r"loss(es)?|logits?|grads?|gradients?|preds?|predictions?|"
        r"outputs?|y_hat|activations?)$", re.IGNORECASE)

    def _in_step_loop(self, ctx):
        return any(self._LOOP_FUNC.search(fi.name)
                   for fi in ctx.func_stack)

    def on_for(self, node, ctx):
        if self._in_step_loop(ctx):
            self._scan(node.body, ctx)

    def on_while(self, node, ctx):
        if self._in_step_loop(ctx):
            self._scan(node.body, ctx)

    def _device_callee(self, call):
        """True when a call plausibly returns a device array: a step/
        model/loss-named callable or a jnp/jax.numpy op."""
        name = dotted(call.func)
        if name.startswith(("jnp.", "jax.numpy.")):
            return True
        return any(self._DEVICE_NAMES.match(part)
                   for part in name.split(".") if part)

    def _is_host_conversion(self, call):
        if dotted(call.func) in self._HOST_CASTS:
            return True
        return (isinstance(call.func, ast.Attribute)
                and call.func.attr in self._SYNC_METHODS)

    def _device_value(self, expr, device_names, host_names):
        """The device-ish thing accumulated by ``expr`` (a name), or
        None.  Host conversions prune the walk: float(loss) is safe,
        and so is a name rebound from one (`loss = float(raw)`)."""
        stack = [expr]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Call):
                if self._is_host_conversion(n):
                    continue  # converted to host — and its args with it
                if self._device_callee(n):
                    return f"{dotted(n.func)}()"
                stack.extend(n.args)
                stack.extend(kw.value for kw in n.keywords)
                continue
            if isinstance(n, ast.Name):
                if n.id in host_names:
                    continue
                if n.id in device_names \
                        or self._DEVICE_NAMES.match(n.id):
                    return n.id
                continue
            stack.extend(ast.iter_child_nodes(n))
        return None

    def _scan(self, body, ctx):
        # names bound to a device-call result earlier in THIS loop body
        # (`loss = step(x, y)`); any other rebind (host conversion,
        # constant) moves the name to the host set
        device_names = set()
        host_names = set()

        def walk(n):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef, ast.For,
                              ast.AsyncFor, ast.While)):
                return  # nested loops get their own on_for/on_while
            if isinstance(n, ast.Assign):
                names = [sub.id for t in n.targets
                         for sub in ast.walk(t)
                         if isinstance(sub, ast.Name)]
                if (isinstance(n.value, ast.Call)
                        and not self._is_host_conversion(n.value)
                        and self._device_callee(n.value)):
                    device_names.update(names)
                    host_names.difference_update(names)
                else:
                    device_names.difference_update(names)
                    host_names.update(names)
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in self._ACCUM_METHODS):
                for arg in n.args:
                    what = self._device_value(arg, device_names,
                                              host_names)
                    if what:
                        recv = dotted(n.func.value) or "container"
                        ctx.report(
                            n, self.id,
                            f"{recv}.{n.func.attr}({what}) accumulates "
                            f"a device array per step — every buffer "
                            f"stays live in HBM until the container "
                            f"dies; append float(x)/.item() on the "
                            f"logging cadence instead")
                        break
            for c in ast.iter_child_nodes(n):
                walk(c)

        for stmt in body:
            walk(stmt)


@register
class HostSideNanCheck(Rule):
    id = "TPU017"
    name = "host-side-nan-check"
    rationale = ("pulling a value to the host just to ask `isnan` — "
                 "math.isnan(float(loss)), np.isnan(x.numpy()), "
                 "bool(jnp.isnan(...)) — stalls the device pipeline "
                 "every step for a check the device can run for free; "
                 "fold the flag into the jitted step "
                 "(observability.numerics.health_outputs) and read it "
                 "asynchronously at a cadence "
                 "(NumericsMonitor.watch)")

    _NAN_FUNCS = {"isnan", "isinf", "isfinite"}
    _SYNC_METHODS = {"item", "numpy", "tolist", "__array__"}
    # host casts/transfers that force the device->host sync
    _SYNC_WRAPPERS = {"bool", "float", "int", "np.asarray", "np.array",
                      "numpy.asarray", "numpy.array", "jax.device_get",
                      "device_get"}
    # same scope gate as TPU007: library code, or any function whose
    # name says it is a training loop
    _LOOP_FUNC = re.compile(r"(train|fit|epoch|run_steps?|step_loop)",
                            re.IGNORECASE)

    def _applicable(self, ctx):
        return ctx.library_path or any(
            self._LOOP_FUNC.search(fi.name) for fi in ctx.func_stack)

    def _walk_calls(self, tree):
        """Call nodes under ``tree`` (itself included), skipping
        deferred-execution bodies."""
        stack = [tree]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(n, ast.Call):
                yield n
            stack.extend(ast.iter_child_nodes(n))

    def _has_nan_call(self, tree):
        return any(
            dotted(c.func).rpartition(".")[2] in self._NAN_FUNCS
            for c in self._walk_calls(tree))

    def _has_sync(self, tree):
        for c in self._walk_calls(tree):
            if (isinstance(c.func, ast.Attribute)
                    and c.func.attr in self._SYNC_METHODS):
                return True
            if dotted(c.func) in self._SYNC_WRAPPERS:
                return True
        return False

    def on_call(self, node, ctx):
        if not self._applicable(ctx):
            return
        name = dotted(node.func)
        # spelling 1: sync method chained onto the device-side check —
        # jnp.isnan(loss).item(), jnp.any(jnp.isnan(g)).numpy()
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in self._SYNC_METHODS
                and self._has_nan_call(node.func.value)):
            ctx.report(node, self.id,
                       f".{node.func.attr}() on a device-side nan/inf "
                       f"check syncs the host every call; compile the "
                       f"flag into the step (numerics.health_outputs) "
                       f"and read it at a cadence")
            return
        # spelling 2: host cast wrapped around the device-side check —
        # bool(jnp.any(~jnp.isfinite(g))), np.asarray(jnp.isnan(x))
        if name in self._SYNC_WRAPPERS and node.args:
            arg = node.args[0]
            # an inner sync already carries the report (spelling 1/3)
            if self._has_nan_call(arg) and not self._has_sync(arg):
                ctx.report(node, self.id,
                           f"{name}() around a device-side nan/inf "
                           f"check forces a blocking device->host sync; "
                           f"compile the flag into the step "
                           f"(numerics.health_outputs) and read it at "
                           f"a cadence")
            return
        # spelling 3: host-side check fed by an explicit sync —
        # math.isnan(float(loss)), np.isnan(x.numpy())
        if (name.rpartition(".")[2] in self._NAN_FUNCS
                and any(self._has_sync(a) for a in node.args)):
            ctx.report(node, self.id,
                       f"{name}() over a synced host value checks "
                       f"non-finiteness one device round-trip too "
                       f"late; compile the flag into the step "
                       f"(numerics.health_outputs) and read it at a "
                       f"cadence")


@register
class ImportTimeEnvRead(Rule):
    id = "TPU020"
    name = "env-read-at-import-time"
    rationale = ("os.environ read at module import time freezes the "
                 "value at whatever the environment held when the module "
                 "first loaded — exports made after import are silently "
                 "ignored, tests can't override the knob without a "
                 "module reload, and the launcher's per-worker env "
                 "injection races the import order; read the variable "
                 "lazily inside the function that needs it (the repo's "
                 "PT_* knobs all resolve at call time for this reason). "
                 "tools/, tests and CLI entry points are exempt")

    _ENV_CALLS = {"os.getenv", "getenv", "os.environ.get", "environ.get",
                  "os.environ.setdefault", "environ.setdefault"}
    _ENV_OBJS = {"os.environ", "environ"}

    def _applicable(self, node, ctx):
        # module scope only (class bodies included — they run at
        # import); function bodies are the lazy pattern we want
        if not ctx.library_path or ctx.func_stack:
            return False
        # a module-level `lambda: os.getenv(...)` defers the read — the
        # Linter doesn't push a scope for lambdas, so span-check here
        spans = getattr(ctx, "_tpu020_lambda_spans", None)
        if spans is None:
            spans = [(n.lineno, getattr(n, "end_lineno", n.lineno))
                     for n in ast.walk(ctx._tree)
                     if isinstance(n, ast.Lambda)]
            ctx._tpu020_lambda_spans = spans
        line = getattr(node, "lineno", 0)
        return not any(lo <= line <= hi for lo, hi in spans)

    def on_call(self, node, ctx):
        if not self._applicable(node, ctx):
            return
        name = dotted(node.func)
        if name in self._ENV_CALLS:
            ctx.report(node, self.id,
                       f"{name}() at module import time pins the value "
                       f"at first-load; resolve the variable lazily "
                       f"inside the function that uses it")

    def on_assign(self, node, ctx):
        # subscript reads (`X = os.environ["K"]`) aren't calls; catch
        # them on the assignment event
        if not self._applicable(node, ctx):
            return
        value = getattr(node, "value", None)
        if value is None:
            return
        for sub in ast.walk(value):
            if (isinstance(sub, ast.Subscript)
                    and isinstance(sub.ctx, ast.Load)
                    and dotted(sub.value) in self._ENV_OBJS):
                ctx.report(node, self.id,
                           f"{dotted(sub.value)}[...] read at module "
                           f"import time pins the value at first-load; "
                           f"resolve the variable lazily inside the "
                           f"function that uses it")
                return


@register
class RawQuantDtypeCast(Rule):
    id = "TPU022"
    name = "raw-quant-dtype-cast-outside-quant-layers"
    rationale = ("a bare astype(int8)/view(int8) outside paddle_tpu/ops/ "
                 "and paddle_tpu/quantization/ is a lossy cast with no "
                 "scale attached — astype saturates/wraps without "
                 "recording the absmax, view reinterprets bytes, and "
                 "either way the consumer can't dequantize; the "
                 "framework's quant numerics live in "
                 "ops/quant_kernels.py (quantize_weight/quantize_kv "
                 "return the int8 payload WITH its scale) and the "
                 "observer machinery in quantization/ — route casts "
                 "through them so every int8 tensor in flight carries "
                 "its dequant contract")

    _CAST_ATTRS = {"astype", "view"}
    _QUANT_DTYPES = {"int8", "int4", "uint4",
                     "float8_e4m3fn", "float8_e5m2"}
    # astype(uint8) is the image-pixel idiom (vision transforms) and
    # stays legal; view(uint8) is a byte reinterpretation and is not
    _VIEW_ONLY_DTYPES = {"uint8"}
    # the layers that OWN quant casts: the kernel/dispatch layer and the
    # observer/fake-quant machinery
    _EXEMPT = re.compile(r"(^|/)paddle_tpu/(ops|quantization)(/|$)")

    def _quant_dtype(self, node, allowed):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value if node.value in allowed else None
        name = dotted(node)
        if name.rpartition(".")[2] in allowed:
            return name
        return None

    def on_call(self, node, ctx):
        if not ctx.library_path or self._EXEMPT.search(ctx.path_posix):
            return
        f = node.func
        if not isinstance(f, ast.Attribute) or f.attr not in self._CAST_ATTRS:
            return
        allowed = self._QUANT_DTYPES if f.attr == "astype" \
            else self._QUANT_DTYPES | self._VIEW_ONLY_DTYPES
        dtype_exprs = list(node.args) + [kw.value for kw in node.keywords
                                         if kw.arg == "dtype"]
        for expr in dtype_exprs:
            dt = self._quant_dtype(expr, allowed)
            if dt:
                ctx.report(node, self.id,
                           f".{f.attr}({dt}) outside the quant layers "
                           f"drops the scale the int8 payload needs; use "
                           f"ops.quant_kernels.quantize_weight/"
                           f"quantize_kv (payload + scale together) or "
                           f"move the cast into paddle_tpu/ops/")
                return


@register
class RequestPathCompile(Rule):
    id = "TPU019"
    name = "request-path-compile"
    rationale = ("the serving engine's SLO contract is ZERO compiles on "
                 "the request path — every serveable shape is "
                 "AOT-compiled into the bucket ladder at engine load, "
                 "and any later compile books "
                 "pt_serve_unexpected_compiles_total and trips /healthz; "
                 "a jax.jit/pjit/lower() reachable from serving "
                 "request-handling code stalls a live request behind an "
                 "XLA compile (seconds, not microseconds) the first time "
                 "an unplanned shape arrives — move the compile into the "
                 "engine's build/warmup phase and extend the bucket "
                 "ladder instead")

    _JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit",
                  "jax.experimental.pjit.pjit"}
    # engine phases that are ALLOWED to compile: the AOT build/warmup
    # surface (ServingEngine._build_programs and friends)
    _BUILD_FUNC = re.compile(
        r"(build|warm|aot|compile|lower|export|program|canary|load|init)",
        re.IGNORECASE)

    def _in_build_phase(self, ctx):
        return any(self._BUILD_FUNC.search(fi.name)
                   for fi in ctx.func_stack)

    def on_call(self, node, ctx):
        if not ctx.serving_path or self._in_build_phase(ctx):
            return
        name = dotted(node.func)
        if name in self._JIT_NAMES:
            ctx.report(node, self.id,
                       f"{name}() on the serving request path compiles "
                       f"on first call and stalls a live request; "
                       f"AOT-compile it in the engine's "
                       f"_build_programs/warmup phase and serve from "
                       f"the bucket ladder")
            return
        # AOT entry points invoked outside the build phase:
        # jit(f).lower(...) chains, or .lower(...)/.aot_compile(...)
        # on a stored jitted callable.  str.lower() takes no
        # arguments, so an argumentful .lower(...) is an XLA lowering.
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "lower", "aot_compile"):
            if node.args or node.keywords or (
                    isinstance(node.func.value, ast.Call)
                    and dotted(node.func.value.func) in self._JIT_NAMES):
                ctx.report(node, self.id,
                           f".{node.func.attr}() on the serving request "
                           f"path triggers XLA lowering+compilation "
                           f"mid-request; precompile every bucket shape "
                           f"at engine load (the zero-compile sentinel "
                           f"will book this as an SLO violation)")


@register
class UnboundedBlockingCall(Rule):
    id = "TPU021"
    name = "unbounded-blocking-call"
    rationale = ("a .join()/.wait()/.result()/.acquire() with no timeout "
                 "on a serving or distributed request path turns a hung "
                 "peer into a hung server: the caller blocks forever, "
                 "holds its KV pages/locks, and is indistinguishable "
                 "from load to everything upstream — the exact failure "
                 "the serve hang watchdog and drain budgets exist to "
                 "bound.  Pass a timeout (retry in a loop if the wait "
                 "is legitimately long) so a wedged dependency surfaces "
                 "as a timeout the resilience layer can act on instead "
                 "of an invisible stall")

    _BLOCKING = {"join", "wait", "result", "acquire"}
    _TIMEOUT_KWARGS = {"timeout", "timeout_s", "timeout_ms", "deadline"}

    def on_call(self, node, ctx):
        # request-path discipline only: serving/ and the distributed
        # control planes (fleet, collective, drill supervisors)
        if not (ctx.serving_path or ctx.distributed_path):
            return
        f = node.func
        if not isinstance(f, ast.Attribute) or f.attr not in self._BLOCKING:
            return
        # a positional arg (join(5), wait(0.1), acquire(False)) or an
        # explicit timeout/deadline kwarg bounds the call
        if node.args:
            return
        if any(kw.arg in self._TIMEOUT_KWARGS for kw in node.keywords):
            return
        if f.attr == "acquire" and any(
                kw.arg == "blocking" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False for kw in node.keywords):
            return  # non-blocking acquire
        # wrapper deferral: `self.wait()` where this same file defines
        # a `wait` — the wrapper's own body gets linted instead, so a
        # bounded implementation isn't flagged at every internal call
        if dotted(f.value) == "self" and f.attr in ctx._pre.by_name:
            return
        ctx.report(node, self.id,
                   f".{f.attr}() with no timeout blocks this "
                   f"serving/distributed path forever if the other side "
                   f"is wedged; pass a timeout (looping if needed) so a "
                   f"hang surfaces as an actionable error")


@register
class SignalHandlerInLibrary(Rule):
    id = "TPU023"
    name = "signal-handler-in-library"
    rationale = ("signal.signal() registers a PROCESS-global handler — "
                 "there is exactly one disposition per signal, so a "
                 "library module installing one silently evicts the "
                 "owner's (the preemption checkpoint hook, the serving "
                 "drain handler, the launcher's fleet killer) and is "
                 "evicted in turn, which is how a preemption SIGTERM "
                 "stops saving checkpoints; handlers belong to process "
                 "OWNERS — the sanctioned entrypoints "
                 "(fleet/elastic/preemption.py, distributed/launch/, "
                 "serving/http.py's drain installer, the observability "
                 "aggregator's main) — and library code should raise, "
                 "return errors, or accept a callback instead")

    _SIGNAL_CALLS = {"signal.signal", "signal.sigaction", "_signal.signal"}
    # the process-owner surfaces that legitimately install handlers:
    # preemption hook, launcher entrypoints, the serving drain
    # installer, and the aggregator daemon's main
    _SANCTIONED = re.compile(
        r"(^|/)paddle_tpu/(fleet/elastic/preemption\.py"
        r"|distributed/fleet/elastic/preemption\.py"
        r"|distributed/launch/"
        r"|serving/http\.py"
        r"|observability/aggregator\.py)")

    def on_call(self, node, ctx):
        if not ctx.library_path or self._SANCTIONED.search(ctx.path_posix):
            return
        name = dotted(node.func)
        if name in self._SIGNAL_CALLS:
            ctx.report(node, self.id,
                       f"{name}() in library code evicts the process "
                       f"owner's handler (preemption save, serving "
                       f"drain, launcher kill); only the sanctioned "
                       f"entrypoints install handlers — accept a "
                       f"callback or surface an error instead")


@register
class HostNondeterminismInStep(Rule):
    id = "TPU024"
    name = "host-nondeterminism-in-captured-step"
    rationale = ("a nondeterministic host call (time.time(), module-"
                 "level random.*/np.random.* draws, os.urandom, "
                 "uuid.uuid4) inside a traced function is either baked "
                 "in as a compile-time constant (silently frozen at "
                 "first trace) or re-evaluated per step on the HOST — "
                 "and in both cases evaluates DIFFERENTLY on each dp "
                 "replica, so bit-identical replicas diverge without "
                 "any hardware fault and the SDC consensus fingerprint "
                 "vote fingers a healthy rank as corrupt; the same "
                 "hazard hides in host-side step/train loops when such "
                 "a call feeds a tensor constructor or PRNG key.  "
                 "Thread randomness in as a seeded, rank-agnostic "
                 "jax.random key (fold_in(key, step)) or an explicit "
                 "traced input instead")

    # exact nondeterministic host calls.  perf_counter/monotonic are
    # deliberately absent: timing reads are legitimate host telemetry
    # and never belong in tensors anyway — flagging them would bury
    # the signal
    _NONDET = {
        "time.time", "time.time_ns", "os.urandom",
        "uuid.uuid4", "uuid.uuid1",
        "datetime.now", "datetime.utcnow",
        "datetime.datetime.now", "datetime.datetime.utcnow",
    }
    # module-level stateful PRNG draws (random.random(), np.random.*):
    # the global generator's state differs across replicas
    _NONDET_PREFIXES = ("random.", "np.random.", "numpy.random.")
    # names under those prefixes that ARE the seeded discipline —
    # seeding calls and explicit-generator constructors
    _SEEDED_OK = {"seed", "RandomState", "default_rng", "Generator",
                  "get_state", "set_state"}
    # host-side training surfaces: a step/train-named function on the
    # call stack marks the per-step loop
    _STEP_FUNC = re.compile(r"(^|_)(step|train)(_|$)")
    # tensor sinks: a nondet call nested in these args crosses onto
    # the device and into the replicated state
    _SINKS = {"to_tensor", "array", "asarray", "full", "constant",
              "PRNGKey", "key", "fold_in", "seed"}

    def _is_nondet(self, name: str) -> bool:
        if name in self._NONDET:
            return True
        for p in self._NONDET_PREFIXES:
            if name.startswith(p):
                return name.rpartition(".")[2] not in self._SEEDED_OK
        return False

    def on_call(self, node, ctx):
        if not ctx.library_path:
            return
        name = dotted(node.func)
        if ctx.innermost_traced() is not None:
            # under a trace ANY nondeterministic host call is a replica-
            # divergence hazard, tensor-bound or not
            if self._is_nondet(name):
                ctx.report(node, self.id,
                           f"{name}() under jit/grad tracing is frozen "
                           f"at trace time (or re-runs per step on the "
                           f"host) with a DIFFERENT value on every dp "
                           f"replica — replicas diverge bit-for-bit and "
                           f"the SDC consensus vote fingers a healthy "
                           f"rank; pass it in as a traced input or "
                           f"derive it from a seeded key")
            return
        # host side: only step/train loops, and only when the nondet
        # value actually feeds a tensor sink — host-only uses (log
        # timestamps, run ids) are fine
        if name.rpartition(".")[2] not in self._SINKS:
            return
        if not any(self._STEP_FUNC.search(fi.name)
                   for fi in ctx.func_stack):
            return
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if (isinstance(sub, ast.Call)
                        and self._is_nondet(dotted(sub.func))):
                    src = dotted(sub.func)
                    ctx.report(node, self.id,
                               f"{src}() feeding {name}() in a "
                               f"step/train loop puts a per-replica-"
                               f"different host value into replicated "
                               f"tensor state — dp ranks diverge and "
                               f"the SDC sentry fingers one as corrupt; "
                               f"use a seeded jax.random key "
                               f"(fold_in(key, step)) or a shared "
                               f"traced input")
                    return
