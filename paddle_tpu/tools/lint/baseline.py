"""Baseline handling: grandfather existing violations, gate new ones.

The baseline is a committed text file of violation keys
(``path::RULE::<stripped source line>``).  Matching is a *multiset*
compare: two identical ``x.item()`` lines in one file need two baseline
entries, and fixing one of them without regenerating keeps the gate
green (stale surplus entries are reported separately so they can be
pruned).  Keys carry no line numbers, so edits elsewhere in a file never
invalidate the baseline.
"""
from __future__ import annotations

import os
from collections import Counter

__all__ = ["default_baseline_path", "load_baseline", "write_baseline",
           "diff_against_baseline"]

_HEADER = """\
# tpu-lint baseline — grandfathered violations.
#
# Every entry is `path::RULE::<stripped source line>`.  The gate fails
# only on violations NOT in this file.  Regenerate after intentional
# changes with:
#     python -m paddle_tpu.tools.lint --write-baseline paddle_tpu exp
# Shrink it over time; never grow it to dodge a fix.
"""


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


def load_baseline(path: str) -> Counter:
    """Keys -> allowed count.  A missing file is an empty baseline."""
    counts: Counter = Counter()
    if not os.path.exists(path):
        return counts
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                counts[line] += 1
    return counts


def write_baseline(path: str, violations) -> int:
    keys = sorted(v.key for v in violations)
    with open(path, "w", encoding="utf-8") as f:
        f.write(_HEADER)
        for k in keys:
            f.write(k + "\n")
    return len(keys)


def diff_against_baseline(violations, baseline: Counter):
    """Split ``violations`` into (new, grandfathered) and report stale
    baseline entries that no longer match anything."""
    budget = Counter(baseline)
    new, old = [], []
    for v in violations:  # already sorted by (path, line): deterministic
        if budget[v.key] > 0:
            budget[v.key] -= 1
            old.append(v)
        else:
            new.append(v)
    stale = sorted(k for k, n in budget.items() if n > 0 for _ in range(n))
    return new, old, stale
