"""tpu-lint — tracing-safety and TPU-performance static analyzer.

Pure-``ast`` (no jax import).  See rules.py for the catalog, README
"Static analysis" for the CLI, and tests/test_tpu_lint.py for the
self-clean gate that keeps the tree free of new violations.
"""
from .baseline import (default_baseline_path, diff_against_baseline,
                       load_baseline, write_baseline)
from .core import (Linter, Suppressions, Violation, iter_py_files,
                   lint_file, lint_source, run_paths)
from .rules import RULES, default_rules, register, rule_catalog

__all__ = [
    "Linter", "Suppressions", "Violation", "RULES",
    "default_rules", "register", "rule_catalog",
    "lint_source", "lint_file", "iter_py_files", "run_paths",
    "default_baseline_path", "load_baseline", "write_baseline",
    "diff_against_baseline",
]
