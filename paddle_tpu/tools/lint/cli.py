"""tpu-lint CLI.

``python -m paddle_tpu.tools.lint [paths...]`` (or the ``tpu-lint``
console script).  Exit codes: 0 clean against the baseline, 1 new
violations (or unparseable files), 2 usage error.
"""
from __future__ import annotations

import argparse
import sys

from .baseline import (default_baseline_path, diff_against_baseline,
                       load_baseline, write_baseline)
from .core import run_paths
from .rules import default_rules, rule_catalog


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-lint",
        description="AST-based tracing-safety and TPU-performance linter "
                    "for paddle_tpu (pure ast — never executes the "
                    "linted code).")
    p.add_argument("paths", nargs="*", default=["paddle_tpu"],
                   help="files or directories to lint "
                        "(default: paddle_tpu)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file (default: the committed "
                        "tools/lint/baseline.txt)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every violation, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="regenerate the baseline from the current tree "
                        "and exit 0")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma-separated rule ids to run "
                        "(e.g. TPU001,TPU003)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress per-violation output; summary only")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for rid, name, rationale in rule_catalog():
            print(f"{rid}  {name}")
            print(f"       {rationale}")
        return 0

    try:
        select = ([r.strip().upper() for r in args.select.split(",")
                   if r.strip()] if args.select else None)
        rules = default_rules(select)
    except KeyError as e:
        print(f"tpu-lint: {e.args[0]}", file=sys.stderr)
        return 2

    violations, errors = run_paths(args.paths, rules=rules)
    for path, msg in sorted(errors.items()):
        print(f"{path}: ERROR {msg}", file=sys.stderr)

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        n = write_baseline(baseline_path, violations)
        print(f"tpu-lint: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} to {baseline_path}")
        return 0

    if args.no_baseline:
        new, old, stale = violations, [], []
    else:
        new, old, stale = diff_against_baseline(
            violations, load_baseline(baseline_path))

    if not args.quiet:
        for v in new:
            print(v)
        for k in stale:
            print(f"stale baseline entry (violation no longer present — "
                  f"prune it): {k}", file=sys.stderr)

    summary = (f"tpu-lint: {len(new)} new violation"
               f"{'' if len(new) == 1 else 's'}")
    if old:
        summary += f", {len(old)} baselined"
    if stale:
        summary += f", {len(stale)} stale baseline entries"
    if errors:
        summary += f", {len(errors)} unparseable files"
    print(summary)
    return 1 if (new or errors) else 0


if __name__ == "__main__":
    sys.exit(main())
