"""tpu-lint core: scope-aware AST walking, suppressions, file running.

The analyzer is a single :class:`Linter` pass per file.  It maintains the
scope state every rule needs (function nesting, loop depth, which
functions are jit/trace targets, which are ``lax.scan``-style bodies,
per-function local bindings) and dispatches structural events to the
rules registered in :mod:`.rules`.  Rules never re-walk the tree.

Two properties matter for a lint gate that runs in CI forever:

- **Never executes the linted code.**  Linting is pure
  ``ast``/``tokenize``: no file under analysis is imported, so a broken
  or accelerator-requiring module still gets linted.
- **Stable violation keys.**  Baseline entries are keyed on
  ``path::RULE::<stripped source line>`` rather than line numbers, so an
  unrelated edit above a grandfathered violation does not invalidate the
  baseline (same trick as clang-tidy's ``--export-fixes`` baselines).
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

# repo root = parents of paddle_tpu/tools/lint/core.py
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

__all__ = ["Violation", "Suppressions", "FuncInfo", "Linter",
           "lint_source", "lint_file", "iter_py_files", "run_paths"]


@dataclass(frozen=True)
class Violation:
    path: str        # normalized, repo-relative, posix separators
    line: int
    col: int
    rule: str        # "TPU001"
    message: str
    line_text: str = ""

    @property
    def key(self) -> str:
        """Baseline identity — content-addressed, line-number free."""
        return f"{self.path}::{self.rule}::{self.line_text.strip()}"

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.message}")


_DIRECTIVE = re.compile(
    r"#\s*tpu-lint:\s*disable=([A-Za-z]{3}\d{3}(?:\s*,\s*[A-Za-z]{3}\d{3})*"
    r"|all)", re.IGNORECASE)


class Suppressions:
    """Per-line ``# tpu-lint: disable=RULE[,RULE...]`` directives.

    A directive on a code line suppresses that line; a directive on a
    standalone comment line suppresses the next line (pylint semantics).
    ``disable=all`` suppresses every rule.

    Violations are reported at a node's FIRST physical line, but a
    multi-line statement may only have room for the directive on a
    later one (e.g. after the closing paren of a wrapped call) —
    :meth:`is_suppressed` therefore takes the node's full line span
    and honors a directive anywhere inside it.
    """

    def __init__(self, source: str):
        self._by_line: dict[int, set[str]] = {}
        try:
            # a trailing directive anywhere on a multi-line statement
            # must cover the WHOLE logical line: the violation reports
            # at the statement's first physical line, while the closing
            # paren is often the only line with room for the comment.
            # Track the current logical line and spread pending
            # directives over its span at the NEWLINE that ends it.
            pending: list[set[str]] = []
            logical_start: int | None = None
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    m = _DIRECTIVE.search(tok.string)
                    if not m:
                        continue
                    rules = {r.strip().upper()
                             for r in m.group(1).split(",") if r.strip()}
                    line = tok.start[0]
                    self._by_line.setdefault(line, set()).update(rules)
                    if tok.line.lstrip().startswith("#"):
                        # standalone comment: applies to the next line
                        self._by_line.setdefault(line + 1,
                                                 set()).update(rules)
                    else:
                        pending.append(rules)
                    continue
                if tok.type == tokenize.NEWLINE:
                    if pending and logical_start is not None:
                        for ln in range(logical_start, tok.start[0] + 1):
                            for rules in pending:
                                self._by_line.setdefault(
                                    ln, set()).update(rules)
                    pending, logical_start = [], None
                    continue
                if tok.type in (tokenize.NL, tokenize.INDENT,
                                tokenize.DEDENT, tokenize.ENDMARKER):
                    continue
                if logical_start is None:
                    logical_start = tok.start[0]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # unparseable comments never block the AST pass

    def is_suppressed(self, rule: str, line: int,
                      end_line: int | None = None) -> bool:
        rule = rule.upper()
        for ln in range(line, max(end_line or line, line) + 1):
            s = self._by_line.get(ln)
            if s and ("ALL" in s or rule in s):
                return True
        return False


# -- scope bookkeeping ------------------------------------------------------

# dotted names whose call (or decorator) makes the wrapped function a
# trace target: python control flow inside it runs on tracers
_JIT_NAMES = {
    "jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit",
    "to_static", "jit.to_static", "paddle_tpu.jit.to_static",
}
# transforms that trace arg0 (grad-like) — same hazards as jit for
# control flow and leaks, though they don't themselves cache programs
_TRACE_NAMES = _JIT_NAMES | {
    "jax.grad", "jax.value_and_grad", "jax.vjp", "jax.jvp", "jax.vmap",
    "jax.checkpoint", "jax.remat", "checkpoint", "jax.linearize",
}
# structured-control-flow primitives: (dotted name) -> indices of the
# traced body callables among positional args
_SCAN_BODY_ARGS = {
    "lax.scan": (0,), "jax.lax.scan": (0,),
    "lax.while_loop": (0, 1), "jax.lax.while_loop": (0, 1),
    "lax.fori_loop": (2,), "jax.lax.fori_loop": (2,),
    "lax.cond": (1, 2), "jax.lax.cond": (1, 2),
    "lax.switch": (), "jax.lax.switch": (),  # branches start at arg1
    "lax.map": (0,), "jax.lax.map": (0,),
    "lax.associative_scan": (0,), "jax.lax.associative_scan": (0,),
}


def dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('' if not name-like)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if isinstance(node, ast.Call) and not parts:
        # decorator factories: functools.partial(jax.jit, ...) names
        # jax.jit; to_static(...) names to_static.  But a call buried in
        # an attribute chain (np.asarray(x).max) is NOT a dotted name.
        inner = dotted(node.func)
        if inner in ("functools.partial", "partial") and node.args:
            return dotted(node.args[0])
        return inner
    return ""


@dataclass
class FuncInfo:
    node: ast.AST                      # FunctionDef / AsyncFunctionDef
    name: str
    params: set[str]
    is_forward: bool = False           # forward / __call__ method body
    is_traced: bool = False            # jit/grad/vmap target
    is_scan_body: bool = False         # lax.scan / while_loop / cond body
    local_stores: set[str] = field(default_factory=set)
    globals_decl: set[str] = field(default_factory=set)
    loop_depth: int = 0                # loops opened inside THIS function


def _collect_local_stores(fn: ast.AST) -> set[str]:
    """Names bound inside ``fn`` (params, assignments, loop/with targets,
    comprehension targets, inner defs) — everything NOT captured."""
    names: set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        names.add(a.arg)
    for sub in ast.walk(fn):
        if sub is fn:
            continue
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            names.add(sub.id)
        elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            names.add(sub.name)
        elif isinstance(sub, ast.Import):
            names.update(a.asname or a.name.split(".")[0]
                         for a in sub.names)
        elif isinstance(sub, ast.ImportFrom):
            names.update(a.asname or a.name for a in sub.names)
    return names


class _Prepass(ast.NodeVisitor):
    """Mark trace-target and scan-body functions before the rule pass.

    Name resolution is file-global by function name: precise scope
    resolution buys little for lint purposes and costs a symbol table.
    """

    def __init__(self):
        self.by_name: dict[str, list[ast.AST]] = {}
        self.traced: set[int] = set()      # id(funcdef)
        self.scan_bodies: set[int] = set()

    def visit_FunctionDef(self, node):
        self.by_name.setdefault(node.name, []).append(node)
        for dec in node.decorator_list:
            if dotted(dec) in _TRACE_NAMES:
                self.traced.add(id(node))
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _mark(self, arg: ast.AST, bucket: set[int]):
        if isinstance(arg, ast.Name):
            for fn in self.by_name.get(arg.id, ()):
                bucket.add(id(fn))
        elif isinstance(arg, ast.Lambda):
            bucket.add(id(arg))

    def visit_Call(self, node):
        name = dotted(node.func)
        if name in _TRACE_NAMES and node.args:
            self._mark(node.args[0], self.traced)
        body_idx = _SCAN_BODY_ARGS.get(name)
        if body_idx is not None:
            for i in body_idx:
                if i < len(node.args):
                    self._mark(node.args[i], self.scan_bodies)
            if name.endswith("switch"):
                for a in node.args[1:]:
                    self._mark(a, self.scan_bodies)
        self.generic_visit(node)


class Linter(ast.NodeVisitor):
    """One pass over one module; dispatches events to the rules."""

    def __init__(self, path: str, source: str, rules, tree=None):
        self.path = path
        self.source_lines = source.splitlines()
        self.rules = rules
        self.suppressions = Suppressions(source)
        self.violations: list[Violation] = []
        self.func_stack: list[FuncInfo] = []
        self.class_stack: list[str] = []
        self._tree = tree if tree is not None else ast.parse(source)
        self._pre = _Prepass()
        self._pre.visit(self._tree)
        # path-derived context
        p = path.replace(os.sep, "/")
        self.path_posix = p
        self.kernel_path = bool(re.search(
            r"(^|/)(ops|kernels|nn/functional)(/|$)", p))
        self.distributed_path = bool(re.search(
            r"(^|/)(distributed|fleet|collective)(/|\.py$|$)", p))
        self.core_path = bool(re.search(r"(^|/)core(/|\.py$|$)", p))
        # the serving request path: zero-compile discipline (TPU019)
        self.serving_path = bool(re.search(r"(^|/)serving(/|$)", p))
        # library code proper: inside the paddle_tpu package but not its
        # CLI/developer-tool surfaces (whose contract IS stdout)
        self.library_path = bool(
            re.search(r"(^|/)paddle_tpu(/|$)", p)
            and not re.search(r"(^|/)(tests?|tools)(/|$)"
                              r"|(^|/)(cli|__main__)\.py$", p))

    # -- context helpers used by rules --------------------------------

    @property
    def current_func(self) -> FuncInfo | None:
        return self.func_stack[-1] if self.func_stack else None

    @property
    def in_loop(self) -> bool:
        """Inside a python loop of the innermost function (or module)."""
        if self.func_stack:
            return self.func_stack[-1].loop_depth > 0
        return self._module_loop_depth > 0

    def innermost_traced(self) -> FuncInfo | None:
        for fi in reversed(self.func_stack):
            if fi.is_traced or fi.is_scan_body:
                return fi
        return None

    def in_forward(self) -> bool:
        return any(fi.is_forward for fi in self.func_stack)

    def enclosing_name_matches(self, pattern: str) -> bool:
        rex = re.compile(pattern)
        return any(rex.search(fi.name) for fi in self.func_stack)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.source_lines):
            return self.source_lines[lineno - 1]
        return ""

    def report(self, node: ast.AST, rule: str, message: str):
        line = getattr(node, "lineno", 1)
        # honor a directive anywhere on the node's physical span: for
        # compound statements (With/For/If...) the span stops before
        # the body so a directive deep inside a block never bleeds
        # onto the header's own violations
        body = getattr(node, "body", None)
        if isinstance(body, list) and body \
                and hasattr(body[0], "lineno"):
            end = max(line, body[0].lineno - 1)
        else:
            end = getattr(node, "end_lineno", None) or line
        if self.suppressions.is_suppressed(rule, line, end):
            return
        self.violations.append(Violation(
            self.path, line, getattr(node, "col_offset", 0) + 1,
            rule, message, self.line_text(line)))

    # -- traversal ----------------------------------------------------

    _module_loop_depth = 0

    def run(self) -> list[Violation]:
        self.visit(self._tree)
        self.violations.sort(key=lambda v: (v.line, v.col, v.rule))
        return self.violations

    def _dispatch(self, hook: str, node: ast.AST):
        for rule in self.rules:
            fn = getattr(rule, hook, None)
            if fn is not None:
                fn(node, self)

    def visit_ClassDef(self, node):
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node):
        fi = FuncInfo(
            node=node, name=node.name,
            params=_param_names(node),
            is_forward=(node.name in ("forward", "__call__")
                        and bool(self.class_stack)),
            is_traced=id(node) in self._pre.traced,
            is_scan_body=id(node) in self._pre.scan_bodies,
            local_stores=_collect_local_stores(node),
        )
        for stmt in ast.walk(node):
            if isinstance(stmt, ast.Global):
                fi.globals_decl.update(stmt.names)
        self.func_stack.append(fi)
        self._dispatch("on_funcdef", node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _visit_loop(self, node):
        is_for = isinstance(node, (ast.For, ast.AsyncFor))
        self._dispatch("on_for" if is_for else "on_while", node)
        if is_for:
            # the iterable evaluates ONCE — jit built in the iterable
            # expression is not per-iteration work
            self.visit(node.target)
            self.visit(node.iter)
        if self.func_stack:
            self.func_stack[-1].loop_depth += 1
        else:
            self._module_loop_depth += 1
        if not is_for:
            self.visit(node.test)  # while-test re-evaluates per iteration
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        if self.func_stack:
            self.func_stack[-1].loop_depth -= 1
        else:
            self._module_loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def visit_If(self, node):
        self._dispatch("on_if", node)
        self.generic_visit(node)

    def visit_Call(self, node):
        self._dispatch("on_call", node)
        self.generic_visit(node)

    def visit_Assign(self, node):
        self._dispatch("on_assign", node)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._dispatch("on_assign", node)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node):
        self._dispatch("on_except", node)
        self.generic_visit(node)

    def visit_With(self, node):
        self._dispatch("on_with", node)
        self.generic_visit(node)

    visit_AsyncWith = visit_With


def _param_names(fn: ast.AST) -> set[str]:
    # *args/**kwargs are python containers — truthiness on them is
    # static even when the elements are tracers, so they are not
    # traced-value names for rule purposes
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    names.discard("self")
    names.discard("cls")
    return names


# -- running ----------------------------------------------------------------

def normalize_path(path: str) -> str:
    """Repo-relative posix path when under the repo, else cwd-relative,
    else absolute.  Baseline keys must not depend on where the CLI ran."""
    ap = os.path.abspath(path)
    for root in (_REPO_ROOT, os.getcwd()):
        try:
            rel = os.path.relpath(ap, root)
        except ValueError:  # different drive (windows)
            continue
        if not rel.startswith(".."):
            return rel.replace(os.sep, "/")
    return ap.replace(os.sep, "/")


def lint_source(source: str, path: str = "<string>",
                rules=None) -> list[Violation]:
    """Lint a source string (unit-test entry point — no filesystem)."""
    if rules is None:
        from .rules import default_rules
        rules = default_rules()
    tree = ast.parse(source)
    return Linter(normalize_path(path) if path != "<string>" else path,
                  source, rules, tree=tree).run()


def lint_file(path: str, rules=None) -> list[Violation]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return lint_source(source, path=path, rules=rules)


_SKIP_DIRS = {"__pycache__", ".git", "build", "dist", ".eggs",
              "node_modules"}


def iter_py_files(paths):
    """Expand files/dirs into a sorted, de-duplicated .py file list."""
    seen, out = set(), []
    for p in paths:
        if os.path.isfile(p):
            cands = [p]
        else:
            cands = []
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in _SKIP_DIRS
                                 and not d.startswith("."))
                cands.extend(os.path.join(root, f)
                             for f in sorted(files) if f.endswith(".py"))
        for c in cands:
            ap = os.path.abspath(c)
            if ap not in seen:
                seen.add(ap)
                out.append(c)
    return out


def run_paths(paths, rules=None):
    """Lint every .py under ``paths``.

    Returns ``(violations, errors)`` where ``errors`` maps path ->
    message for files that failed to parse (reported, never fatal: a
    syntax error in one file must not green-light the rest).
    """
    if rules is None:
        from .rules import default_rules
        rules = default_rules()
    violations: list[Violation] = []
    errors: dict[str, str] = {}
    for f in iter_py_files(paths):
        try:
            violations.extend(lint_file(f, rules=rules))
        except SyntaxError as e:
            errors[normalize_path(f)] = f"syntax error: {e.msg} " \
                                        f"(line {e.lineno})"
        except (OSError, UnicodeDecodeError, RecursionError) as e:
            errors[normalize_path(f)] = f"{type(e).__name__}: {e}"
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations, errors
