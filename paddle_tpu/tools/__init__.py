"""Developer tooling (static analysis, codegen helpers).

Deliberately empty: the lint modules themselves are pure ``ast`` — the
only jax cost of ``python -m paddle_tpu.tools.lint`` is the parent
package import, so the CLI works on accelerator-free boxes.
"""
