"""``paddle.batch`` (ref: ``python/paddle/batch.py``)."""

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """Wrap an instance reader into a mini-batch reader."""
    if batch_size <= 0:
        raise ValueError(
            f"batch_size should be a positive integer, got {batch_size}")

    def batch_reader():
        buf = []
        for instance in reader():
            buf.append(instance)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
