"""Unique name generator (ref: ``python/paddle/utils/unique_name.py`` →
``fluid/unique_name.py``): per-prefix counters with swappable generators so
``guard`` gives a fresh namespace (used by Program clones / to_static)."""
from __future__ import annotations

import contextlib

__all__ = ["generate", "switch", "guard"]


class UniqueNameGenerator:
    def __init__(self, prefix=""):
        self.ids = {}
        self.prefix = prefix

    def __call__(self, key):
        tmp = self.ids.setdefault(key, 0)
        self.ids[key] = tmp + 1
        return self.prefix + "_".join([key, str(tmp)])


generator = UniqueNameGenerator()


def generate(key):
    return generator(key)


def switch(new_generator=None):
    global generator
    old = generator
    generator = new_generator if new_generator is not None \
        else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    elif isinstance(new_generator, bytes):
        new_generator = UniqueNameGenerator(new_generator.decode())
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
