"""Custom C++ op toolchain (ref:
``python/paddle/utils/cpp_extension/cpp_extension.py``).

The reference JIT-compiles CUDA/C++ custom operators against libpaddle and
registers them into the op registry. The TPU compute path is XLA, so device
code cannot be injected post-hoc — custom *device* ops are Pallas kernels +
PyLayer (see ``incubate/nn``). What native extensions still buy on this
stack is host-side work (readers, tokenizers, samplers), so:

 - ``load(name, sources)`` JIT-compiles C++ into a shared library cached by
   source hash (same atomic-rename scheme as ``core/build.py``) and returns
   a ``ctypes.CDLL``.
 - ``setup``/``CppExtension``/``BuildExtension`` wrap setuptools for
   ahead-of-time builds of CPython extension modules, mirroring the
   reference's entry points.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile

__all__ = ["CppExtension", "CUDAExtension", "load", "setup", "BuildExtension",
           "get_build_directory"]


def get_build_directory(verbose=False):
    d = os.environ.get(
        "PADDLE_TPU_EXTENSION_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def CppExtension(sources, *args, **kwargs):
    """A setuptools Extension pre-configured for this toolchain."""
    from setuptools import Extension
    kwargs.setdefault("language", "c++")
    extra = list(kwargs.pop("extra_compile_args", []) or [])
    if not any(a.startswith("-std=") for a in extra):
        extra.append("-std=c++17")
    kwargs["extra_compile_args"] = extra
    name = kwargs.pop("name", "paddle_tpu_ext")
    return Extension(name, sources, *args, **kwargs)


class BuildExtension:
    """build_ext factory matching the reference's ``BuildExtension.with_options``."""

    @classmethod
    def with_options(cls, **options):
        from setuptools.command.build_ext import build_ext

        class _Cmd(build_ext):
            def build_extensions(self):
                for ext in self.extensions:
                    ext.name = options.get("name", ext.name)
                super().build_extensions()

        return _Cmd


def setup(**attrs):
    from setuptools import setup as _setup
    attrs.setdefault("cmdclass", {})
    attrs["cmdclass"].setdefault(
        "build_ext", BuildExtension.with_options(
            name=attrs.get("name", "paddle_tpu_ext")))
    return _setup(**attrs)


def load(name, sources, extra_cxx_flags=None, build_directory=None,
         verbose=False, **_ignored):
    """JIT-compile C++ ``sources`` into ``lib<name>-<hash>.so`` and load it.

    Returns a ``ctypes.CDLL``; call exported ``extern "C"`` symbols
    directly, or wire them into a PyLayer for autograd.
    """
    import ctypes

    build_directory = build_directory or get_build_directory()
    flags = list(extra_cxx_flags or [])
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(flags).encode())
    so_path = os.path.join(build_directory,
                           f"lib{name}-{h.hexdigest()[:16]}.so")
    if not os.path.exists(so_path):
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=build_directory)
        os.close(fd)
        cmd = (["g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
                "-o", tmp] + flags + list(sources))
        if verbose:
            print(" ".join(cmd))
        res = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=600)
        if res.returncode != 0:
            os.unlink(tmp)
            raise RuntimeError(f"extension '{name}' build failed:\n"
                               f"{res.stderr}")
        os.replace(tmp, so_path)
    return ctypes.CDLL(so_path)


def CUDAExtension(sources, *args, **kwargs):
    """ref ``utils/cpp_extension/cpp_extension.py CUDAExtension``: this
    is a TPU build — no nvcc toolchain exists; custom device kernels
    come in as Pallas or PJRT plugins instead."""
    raise RuntimeError(
        "CUDAExtension is unavailable in the TPU build: there is no CUDA "
        "toolchain. Use CppExtension for host ops, Pallas for device "
        "kernels, or a PJRT plugin for custom devices.")
