from .cpp_extension import (  # noqa: F401
    CppExtension, CUDAExtension, load, setup, BuildExtension, get_build_directory,
)
