from .cpp_extension import (  # noqa: F401
    CppExtension, load, setup, BuildExtension, get_build_directory,
)
