"""Retry / backoff primitives shared by the distributed stack.

Every rendezvous or commit-wait loop in a preemptible fleet has the same
failure mode: a fixed ``time.sleep`` interval with no jitter and no
deadline.  On a mass restart (the normal case after a TPU maintenance
event) thousands of workers then retry in lockstep against the same
TCPStore / filesystem — a thundering herd that turns a transient blip
into an outage.  This module is the one sanctioned way to wait:

 - :func:`backoff_delays` — the policy: exponential delays with
   symmetric jitter, capped per-try and bounded by a total deadline.
 - :func:`retry_call`    — retry a callable on a filtered set of
   exceptions (TCPStore worker connect, elastic store ops).
 - :func:`wait_until`    — poll a predicate until truthy (commit-marker
   waits, membership convergence), raising a descriptive TimeoutError.

tpu-lint rule TPU009 flags raw ``time.sleep`` poll loops in
``paddle_tpu/distributed/`` and ``paddle_tpu/core/`` that bypass these
primitives.

Deterministic in tests: ``rng``, ``sleep`` and ``clock`` are injectable.
Stdlib-only — importable from ``paddle_tpu.core`` without cycles.
"""
from __future__ import annotations

import random
import time

__all__ = ["backoff_delays", "retry_call", "wait_until"]


def backoff_delays(base=0.05, factor=2.0, max_delay=2.0, jitter=0.25,
                   deadline=None, max_tries=None, rng=None,
                   clock=time.monotonic):
    """Yield successive backoff delays (seconds); the caller sleeps.

    Delay i is ``min(max_delay, base * factor**i)`` scaled by a uniform
    jitter in ``[1-jitter, 1+jitter]``.  The generator stops (raising
    StopIteration to a ``next``, ending a ``for``) once ``max_tries``
    delays were yielded or the ``deadline`` (seconds from first call)
    would be exceeded; each yielded delay is clipped so the caller never
    sleeps past the deadline.
    """
    if base < 0 or factor < 1.0 or not (0.0 <= jitter <= 1.0):
        raise ValueError(f"invalid backoff policy: base={base} "
                         f"factor={factor} jitter={jitter}")
    rng = rng if rng is not None else random
    t0 = clock()
    i = 0
    while max_tries is None or i < max_tries:
        d = min(max_delay, base * factor ** i)
        if jitter:
            d *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
        if deadline is not None:
            remaining = deadline - (clock() - t0)
            if remaining <= 0:
                return
            d = min(d, remaining)
        yield d
        i += 1


def retry_call(fn, *args, retry_on=(Exception,), deadline=None,
               max_tries=None, base=0.05, factor=2.0, max_delay=2.0,
               jitter=0.25, on_retry=None, rng=None, sleep=time.sleep,
               clock=time.monotonic, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying on ``retry_on`` exceptions
    with jittered exponential backoff.

    The first attempt always runs; afterwards the backoff budget
    (``deadline`` seconds total and/or ``max_tries`` retries) decides
    whether to sleep-and-retry or re-raise the last exception.
    ``on_retry(attempt, exc, delay)``, when given, observes each retry
    (log hook).  Exceptions outside ``retry_on`` propagate immediately.
    """
    delays = backoff_delays(base=base, factor=factor, max_delay=max_delay,
                            jitter=jitter, deadline=deadline,
                            max_tries=max_tries, rng=rng, clock=clock)
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            d = next(delays, None)
            if d is None:
                raise
            attempt += 1
            if on_retry is not None:
                on_retry(attempt, e, d)
            sleep(d)


def wait_until(pred, timeout=None, *, desc=None, diag=None, base=0.02,
               factor=1.5, max_delay=0.5, jitter=0.25, rng=None,
               sleep=time.sleep, clock=time.monotonic):
    """Poll ``pred()`` with jittered backoff until it returns a truthy
    value (returned), or ``timeout`` seconds elapse.

    On timeout raises :class:`TimeoutError` naming ``desc`` (or the
    predicate) — a wait that can hang forever with no diagnostic is how
    one dead rank silently wedges a whole job.  ``diag``, when given, is
    called once at timeout and its string return is appended to the
    error (e.g. which barrier ranks never arrived); a failing diag never
    masks the timeout itself.  ``timeout=None`` polls forever (the
    caller owns liveness, e.g. a supervising loop).
    """
    delays = backoff_delays(base=base, factor=factor, max_delay=max_delay,
                            jitter=jitter, deadline=timeout, rng=rng,
                            clock=clock)
    while True:
        value = pred()
        if value:
            return value
        d = next(delays, None)
        if d is None:
            what = desc or getattr(pred, "__name__", repr(pred))
            extra = ""
            if diag is not None:
                try:
                    extra = str(diag() or "")
                except Exception as e:  # diagnostics must not mask timeout
                    extra = f"(diagnostic probe failed: {e})"
            raise TimeoutError(
                f"wait_until: {what} still false after {timeout}s"
                + (f" — {extra}" if extra else ""))
        sleep(d)
