"""``try_import`` (ref: ``python/paddle/utils/lazy_import.py``)."""
import importlib

__all__ = ["try_import"]


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        if err_msg is None:
            err_msg = (f"Failed importing {module_name}. This likely means "
                       f"that some modules require additional dependencies "
                       f"that have to be manually installed (usually with "
                       f"`pip install {module_name}`).")
        raise ImportError(err_msg)
