"""DLPack interop (ref: ``python/paddle/utils/dlpack.py``).

Zero-copy tensor exchange with torch/numpy/cupy via the DLPack protocol;
jax arrays speak it natively, so both directions are thin."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Export a Tensor as a DLPack-protocol object (implements
    ``__dlpack__``/``__dlpack_device__``; consumable by torch/numpy/cupy
    ``from_dlpack``). jax deprecated capsule export in favor of the
    protocol, so the device buffer itself is the exchange object —
    zero-copy either way."""
    if isinstance(x, Tensor):
        x = x._data
    return jnp.asarray(x)


def from_dlpack(dlpack):
    """Import a DLPack capsule / __dlpack__-bearing object as a Tensor."""
    return Tensor(jax.dlpack.from_dlpack(dlpack))
