"""Weight-file resolution (ref: ``python/paddle/utils/download.py``).

This deployment runs with zero egress, so the network leg is gated: a URL
resolves from the local cache (``$PADDLE_TPU_HOME/weights``, plus any dirs
on ``$PADDLE_TPU_WEIGHT_PATH``) and a cache miss raises with the exact path
to drop the file at. md5 verification and archive decompression — the parts
that don't need a network — are fully implemented.
"""
from __future__ import annotations

import hashlib
import os
import os.path as osp
import tarfile
import zipfile

__all__ = ["is_url", "get_weights_path_from_url", "get_path_from_url",
           "weights_home"]

def weights_home() -> str:
    """Weight cache root — resolved lazily so ``PADDLE_TPU_HOME`` set
    after import (tests, launchers) is honored."""
    return osp.join(
        os.environ.get("PADDLE_TPU_HOME",
                       osp.join(osp.expanduser("~"), ".cache",
                                "paddle_tpu")),
        "weights")


def is_url(path):
    return str(path).startswith(("http://", "https://"))


def _search_dirs():
    dirs = [weights_home()]
    extra = os.environ.get("PADDLE_TPU_WEIGHT_PATH", "")
    dirs += [d for d in extra.split(os.pathsep) if d]
    return dirs


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, weights_home(), md5sum)


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True,
                      decompress=True):
    if not is_url(url):
        if osp.exists(url):
            return url
        raise FileNotFoundError(url)
    fname = osp.split(url)[-1]
    for d in ([root_dir] if root_dir else []) + _search_dirs():
        fullname = osp.join(d, fname)
        if osp.exists(fullname):
            if md5sum and not _md5check(fullname, md5sum):
                raise IOError(f"{fullname} exists but fails md5 check")
            if decompress and (tarfile.is_tarfile(fullname)
                               or zipfile.is_zipfile(fullname)):
                return _decompress(fullname)
            return fullname
    raise RuntimeError(
        f"cannot fetch {url}: this build runs without network access. "
        f"Place the file at {osp.join(root_dir or weights_home(), fname)} "
        f"or add its directory to $PADDLE_TPU_WEIGHT_PATH.")


def _md5check(fullname, md5sum=None):
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, 'rb') as f:
        for chunk in iter(lambda: f.read(4096), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def _safe_extractall(tf, dst):
    """extractall with the 'data' path-traversal filter; on Pythons
    predating the filter= backport (3.10.12/3.11.4), validate members
    manually — rejecting absolute/.. paths AND link members (a symlink
    pointing outside dst followed by a file through it escapes even when
    every name looks clean) — instead of extracting unfiltered
    (fail-closed). Shared by every tar extraction site."""
    if hasattr(tarfile, "data_filter"):
        tf.extractall(dst, filter="data")
        return
    for m in tf.getmembers():
        name = m.name
        if name.startswith(("/", "\\")) or ".." in name.split("/"):
            raise ValueError(f"unsafe tar member path: {name!r}")
        if m.issym() or m.islnk():
            raise ValueError(
                f"tar member {name!r} is a link; refusing to extract "
                f"without the 'data' filter")
    tf.extractall(dst)


def _decompress(fname):
    dst_dir = osp.splitext(fname)[0]
    if osp.isdir(dst_dir) and os.listdir(dst_dir):
        return dst_dir
    os.makedirs(dst_dir, exist_ok=True)
    if tarfile.is_tarfile(fname):
        with tarfile.open(fname) as tf:
            _safe_extractall(tf, dst_dir)
    elif zipfile.is_zipfile(fname):
        with zipfile.ZipFile(fname) as zf:
            zf.extractall(dst_dir)
    else:
        raise TypeError(f"unsupported archive: {fname}")
    return dst_dir
