"""Nested-structure helpers (ref: ``python/paddle/utils/layers_utils.py``).

The reference hand-rolls recursion over lists/tuples/dicts; here a nested
structure is exactly a jax pytree, so flatten/pack/map delegate to
``jax.tree_util`` (Tensors are leaves: they are not registered pytree
nodes)."""
from __future__ import annotations

import collections.abc

import jax

__all__ = ["convert_to_list", "is_sequence", "to_sequence", "flatten",
           "pack_sequence_as", "map_structure", "assert_same_structure"]


def convert_to_list(value, n, name, dtype=int):
    """Scalar -> n-list; validating n-sequence passthrough (conv arg glue)."""
    if isinstance(value, dtype):
        return [value] * n
    try:
        value_list = list(value)
    except TypeError:
        raise ValueError(
            f"The {name}'s type must be {dtype} or {n}-elem sequence, "
            f"received {value}")
    if len(value_list) != n:
        raise ValueError(f"The {name} must have {n} elements, got {value}")
    return value_list


def is_sequence(seq):
    if isinstance(seq, dict):
        return True
    return (isinstance(seq, collections.abc.Sequence)
            and not isinstance(seq, str))


def to_sequence(nest):
    return nest if is_sequence(nest) else [nest]


def flatten(nest):
    return jax.tree_util.tree_leaves(
        nest, is_leaf=lambda x: not is_sequence(x))


def pack_sequence_as(structure, flat_sequence):
    treedef = jax.tree_util.tree_structure(
        structure, is_leaf=lambda x: not is_sequence(x))
    return jax.tree_util.tree_unflatten(treedef, flat_sequence)


def map_structure(func, *structure):
    return jax.tree_util.tree_map(
        func, *structure, is_leaf=lambda x: not is_sequence(x))


def assert_same_structure(nest1, nest2, check_types=True):
    t1 = jax.tree_util.tree_structure(
        nest1, is_leaf=lambda x: not is_sequence(x))
    t2 = jax.tree_util.tree_structure(
        nest2, is_leaf=lambda x: not is_sequence(x))
    if t1 != t2:
        raise ValueError(
            f"The two structures don't have the same nested structure: "
            f"{t1} vs {t2}")
