"""``paddle.utils`` (ref: ``python/paddle/utils/__init__.py``).

Structure-tree helpers (`flatten`/`pack_sequence_as`/`map_structure`) ride
``jax.tree_util`` — on this stack a "nested structure" IS a pytree, so the
reference's hand-rolled recursion (``utils/layers_utils.py``) collapses to
registered-pytree traversal.
"""
from . import unique_name  # noqa: F401
from . import retry  # noqa: F401
from .retry import retry_call, wait_until, backoff_delays  # noqa: F401
from . import dlpack  # noqa: F401
from . import download  # noqa: F401
from . import cpp_extension  # noqa: F401
from .deprecated import deprecated  # noqa: F401
from .lazy_import import try_import  # noqa: F401
from .install_check import run_check  # noqa: F401
from .layers_utils import (  # noqa: F401
    convert_to_list, is_sequence, to_sequence, flatten, pack_sequence_as,
    map_structure, assert_same_structure,
)

__all__ = ["deprecated", "run_check", "require_version", "try_import",
           "unique_name", "dlpack", "download", "cpp_extension",
           "retry", "retry_call", "wait_until", "backoff_delays"]


def require_version(min_version, max_version=None):
    """Check the installed framework version against bounds (ref:
    ``python/paddle/fluid/framework.py require_version``)."""
    from .. import __version__

    def _tup(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())

    cur = _tup(__version__)
    if _tup(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version is not None and _tup(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")
    return True
