"""``@deprecated`` decorator (ref: ``python/paddle/utils/deprecated.py``):
prepends a Deprecated note to the docstring and warns once per call site."""
from __future__ import annotations

import functools
import warnings

__all__ = ["deprecated"]


def deprecated(update_to="", since="", reason="", level=0):
    def decorator(func):
        msg = f'API "{func.__module__}.{func.__name__}" is deprecated'
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f', please use "{update_to}" instead'
        if reason:
            msg += f". Reason: {reason}"

        doc = f"""\n\nWarning:\n    {msg}.\n\n"""
        func.__doc__ = doc + (func.__doc__ or "")
        if level == 0:
            return func

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        return wrapper
    return decorator
