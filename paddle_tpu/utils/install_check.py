"""``paddle.utils.run_check`` (ref:
``python/paddle/utils/install_check.py:209``).

Same shape as the reference's check — a tiny linear model is trained one
step in dygraph and once through the compiled (to_static analog) path, then,
when more than one device is visible, a data-parallel step runs over the
full device mesh — but the parallel leg is a GSPMD ``pjit`` over a
``jax.sharding.Mesh`` instead of spawning NCCL worker processes.
"""
from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def _simple_network():
    import paddle_tpu as paddle

    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(4, 8)
            self.out = paddle.nn.Linear(8, 2)

        def forward(self, x):
            return self.out(paddle.nn.functional.relu(self.fc(x)))

    net = Net()
    x = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
    label = paddle.to_tensor(np.random.randint(0, 2, (8,)))
    return net, x, label


def _run_dygraph_single():
    import paddle_tpu as paddle
    net, x, label = _simple_network()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    loss = paddle.nn.functional.cross_entropy(net(x), label)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss.item())


def _run_compiled_single():
    import paddle_tpu as paddle
    net, x, label = _simple_network()

    @paddle.jit.to_static
    def step(x):
        return paddle.nn.functional.cross_entropy(net(x), label)

    return float(step(x).item())


def _run_parallel(devices):
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("dp",))
    w = jax.device_put(np.ones((4, 2), np.float32),
                       NamedSharding(mesh, P()))
    x = jax.device_put(np.random.rand(n * 2, 4).astype(np.float32),
                       NamedSharding(mesh, P("dp")))

    @jax.jit
    def step(w, x):
        return ((x @ w) ** 2).mean()

    return float(step(w, x))


def run_check():
    import jax
    import paddle_tpu as paddle

    devs = jax.devices()
    kind = devs[0].device_kind if devs else "none"
    print(f"Running verify PaddlePaddle-TPU program ... "
          f"({len(devs)} x {kind})")
    _run_dygraph_single()
    _run_compiled_single()
    if len(devs) > 1:
        _run_parallel(devs)
        print(f"PaddlePaddle-TPU works well on {len(devs)} devices.")
    else:
        print("PaddlePaddle-TPU works well on 1 device.")
    print("PaddlePaddle-TPU is installed successfully! Let's start deep "
          "learning with PaddlePaddle-TPU now.")
