"""Independent (ref: ``python/paddle/distribution/independent.py``):
reinterprets trailing batch dims as event dims."""
from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution

__all__ = ["Independent"]


class Independent(Distribution):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        b = tuple(base.batch_shape)
        if self.rank > len(b):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        super().__init__(b[:len(b) - self.rank],
                         b[len(b) - self.rank:] + tuple(base.event_shape))

    def _sample(self, key, shape):
        return self.base._sample(key, shape)

    def _rsample(self, key, shape):
        return self.base._rsample(key, shape)

    def _log_prob(self, value):
        lp = self.base._log_prob(value)
        if self.rank:
            lp = lp.sum(axis=tuple(range(-self.rank, 0)))
        return lp

    def _entropy(self):
        e = self.base._entropy()
        if self.rank:
            e = e.sum(axis=tuple(range(-self.rank, 0)))
        return e

    def _mean(self):
        return self.base._mean()

    def _variance(self):
        return self.base._variance()
